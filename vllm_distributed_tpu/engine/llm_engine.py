"""Synchronous engine: scheduler + executor + detokenization loop.

The TPU-native rebuild of the vLLM engine core the reference consumes via
`build_async_engine_client_from_engine_args` (launch.py:33, 407; SURVEY.md
§2.3).  One `step()` = schedule → executor.execute_model (one fused device
program per worker) → update request state → detokenize/stream.
"""

from __future__ import annotations

import time
from typing import Any

from vllm_distributed_tpu.config import EngineArgs, EngineConfig
from vllm_distributed_tpu.engine.request import (
    FINISH_REASON,
    Request,
    RequestStatus,
)
from vllm_distributed_tpu.engine.scheduler import Scheduler
from vllm_distributed_tpu.engine.spec_decode import spec_eligible
from vllm_distributed_tpu.executor.abstract import Executor
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.outputs import CompletionOutput, RequestOutput
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.tokenizer import (
    IncrementalDetokenizer,
    get_tokenizer,
)
from vllm_distributed_tpu.tracing import get_tracer

logger = init_logger(__name__)


class LLMEngine:
    def __init__(
        self,
        config: EngineConfig,
        executor_class: type[Executor] | None = None,
        metrics=None,
    ) -> None:
        self.config = config
        executor_class = executor_class or Executor.get_class(config)
        self.executor = executor_class(config)
        try:
            self._init_engine(config, metrics)
        except Exception:
            # A half-built engine must not leak its executor (listener
            # socket, loop thread, pools) — the supervisor's crash-loop
            # rebuild attempts would otherwise pile them up.
            self.executor.shutdown()
            raise

    def _init_engine(self, config: EngineConfig, metrics) -> None:
        num_pages = self.executor.determine_num_pages()
        self.executor.initialize_cache(num_pages)
        if config.scheduler_config.warmup_decode:
            self.executor.warmup_decode()
        if config.scheduler_config.warmup_prefill:
            self.executor.warmup_prefill()
        self.scheduler = Scheduler(
            config.scheduler_config, config.cache_config, num_pages
        )

        if metrics is None:
            from vllm_distributed_tpu.metrics import EngineMetrics

            metrics = EngineMetrics(
                config.model_config.model,
                enabled=config.observability_config.collect_metrics,
            )
        # A rebuilt engine (engine/supervisor.py) inherits the previous
        # engine's EngineMetrics so counters/histograms span restarts.
        self.metrics = metrics
        # Tracing (tracing.py): the global tracer is configured from
        # ObservabilityConfig; with tracing off every call below is the
        # allocation-free no-op path.  The metrics sink is a single slot,
        # so supervisor rebuilds re-register the same EngineMetrics
        # without stacking.
        obs = config.observability_config
        self.tracer = get_tracer().configure(
            enabled=obs.enable_tracing, ring_size=obs.trace_ring_size
        )
        self.tracer.set_metrics_sink(self.metrics.observe_span)
        # Liveness instruments (host_up, heartbeat latency) are emitted
        # from the executor's heartbeat loop.
        self.executor.metrics = self.metrics
        self._preemptions_seen = 0
        # (queries, hits, host_hits) already recorded.
        self._prefix_cache_seen = (0, 0, 0)
        self._spec_seen = (0, 0)  # (drafted, accepted) already recorded
        # Tiered KV cache (ISSUE 14): (spilled, restored, slots-used)
        # already recorded, and the per-page pool byte size for the
        # vllm:host_kv_bytes gauge — pulled once from the reply-rank
        # worker over the new kv-tier RPC (best-effort: 0 leaves the
        # gauge at 0, never fails boot).
        self._kv_tier_seen = (0, 0, 0)
        self._kv_page_bytes = 0
        if (
            config.cache_config.enable_prefix_caching
            and config.cache_config.kv_spill_host_pages > 0
        ):
            try:
                info = self.executor.collective_rpc(
                    "get_kv_tier_info",
                    unique_reply_rank=self.executor.output_rank,
                    timeout=30.0,
                )
                self._kv_page_bytes = int((info or {}).get("page_bytes", 0))
            except Exception as e:  # noqa: BLE001 — telemetry only
                logger.debug("kv-tier info pull failed: %s", e)
        # Flight recorder (ISSUE 12): always-on bounded ring of per-step
        # records, dumped on HostFailure/recovery/drain and served at
        # /debug/flightrecorder.
        from vllm_distributed_tpu.engine.flight_recorder import (
            FlightRecorder,
            resilience_state,
        )

        self.flight_recorder = FlightRecorder(
            size=obs.flight_recorder_size
        )
        # Unified timeline (ISSUE 20): dumps become structured events
        # on the engine's sentinel log; step records sample the
        # registered resilience provider (if any shares the process).
        self.flight_recorder.sentinel = self.metrics.events
        self._resilience_state = resilience_state
        # Device-telemetry pull cursors: event-ring position (timing
        # histogram) and cumulative per-kind compile totals already
        # counted (exact even when the bounded event ring overflows
        # between scrapes — the recompile-storm case).
        self._telemetry_seq = 0
        self._telemetry_compiles_seen: dict[str, int] = {}

        # Disaggregated prefill/decode hand-off (ISSUE 15): export holds
        # + inbound transfers.  Always constructed (cheap, idle costs
        # one attribute read per schedule); the scheduler hook makes the
        # finish path hold pages only for prefill_only requests.
        from vllm_distributed_tpu.engine.kv_transfer import (
            KVTransferManager,
        )

        self.kv_transfer = KVTransferManager(
            self.scheduler, self.executor, self.metrics, self.tracer
        )
        self.scheduler.kv_transfer = self.kv_transfer

        self.tokenizer = None
        if not config.model_config.skip_tokenizer_init:
            self.tokenizer = get_tokenizer(
                config.model_config.tokenizer,
                config.model_config.trust_remote_code,
            )
        self.detokenizers: dict[str, IncrementalDetokenizer] = {}
        self._failed = False
        self.failure_info = None  # HostFailure from the executor, if any
        self.executor.register_failure_callback(self._on_failure)
        # Pipelining: dispatched-but-unapplied fused-decode steps (at most
        # one between step() calls, two briefly within a call) — the
        # engine-side realization of the reference's in-flight batches
        # (max_concurrent_batches, launch.py:298-302).
        self._pending: list[tuple[Any, Any]] = []
        # Async-scheduling reconciliation count: times the pipeline had
        # to fully drain because the predicted post-step state was
        # invalidated (stop/EOS/budget mid-window, admissions, logprob
        # requests).  0 at steady-state decode; surfaced by bench-serve
        # as its stall_windows field.
        self.pipeline_breaks = 0

    @classmethod
    def from_engine_args(cls, engine_args: EngineArgs) -> "LLMEngine":
        return cls(engine_args.create_engine_config())

    def _on_failure(self) -> None:
        self._failed = True
        self.failure_info = getattr(self.executor, "failure_info", None)
        detail = (
            f": {self.failure_info.describe()}"
            if self.failure_info is not None
            else ""
        )
        logger.error("executor reported failure; engine is dead%s", detail)
        self.metrics.record_engine_dead(self.failure_info)
        # Capture the last N steps before the incident while the state
        # is fresh — the artifact the post-mortem starts from.
        self.flight_recorder.dump(
            "host_failure",
            extra=(
                self.failure_info.to_dict()
                if self.failure_info is not None
                else None
            ),
        )

    @property
    def errored(self) -> bool:
        """Executor failure observed — the next step() (or the AsyncLLM
        loop's idle check) turns this into engine death."""
        return self._failed

    def _dead_message(self) -> str:
        if self.failure_info is not None:
            return f"Engine executor failed: {self.failure_info.describe()}"
        return "Engine executor failed."

    # ---- intake ----
    def add_request(
        self,
        request_id: str,
        prompt: str | None = None,
        sampling_params: SamplingParams | None = None,
        prompt_token_ids: list[int] | None = None,
        arrival_time: float | None = None,
        trace_ctx: tuple | None = None,
    ) -> None:
        sampling_params = sampling_params or SamplingParams()
        if prompt_token_ids is None:
            if prompt is None:
                raise ValueError("need prompt or prompt_token_ids")
            if self.tokenizer is None:
                raise ValueError("tokenizer not initialized")
            prompt_token_ids = self.tokenizer.encode(prompt)
        eos = None
        if not sampling_params.ignore_eos:
            if self.tokenizer is not None:
                eos = self.tokenizer.eos_token_id
            else:
                eos = getattr(
                    self.config.model_config.hf_config, "eos_token_id", None
                )
                if isinstance(eos, list):
                    eos = eos[0] if eos else None
        req = Request(
            request_id=request_id,
            prompt_token_ids=prompt_token_ids,
            sampling_params=sampling_params,
            prompt=prompt,
            eos_token_id=eos,
            trace_ctx=trace_ctx,
        )
        # Client deadline (deadline_ms param) or server default, anchored
        # to the monotonic arrival instant; enforced at schedule time.
        req.set_deadline(self.config.scheduler_config.default_deadline_ms)
        self.scheduler.add_request(req)
        if (
            sampling_params.detokenize
            and self.tokenizer is not None
        ):
            self.detokenizers[request_id] = IncrementalDetokenizer(
                self.tokenizer,
                prompt_token_ids,
                stop=sampling_params.stop,
                include_stop_str_in_output=(
                    sampling_params.include_stop_str_in_output
                ),
                min_tokens=sampling_params.min_tokens,
            )

    def abort_request(self, request_id: str) -> None:
        self.scheduler.abort_request(request_id)
        self.detokenizers.pop(request_id, None)
        if self.config.kv_transfer_config is not None:
            self.executor.kv_output_aggregator.forget(request_id)

    def has_unfinished_requests(self) -> bool:
        return self.scheduler.has_unfinished_requests()

    # ---- the loop ----
    def _pipeline_safe(self) -> bool:
        """True when the next schedule() is guaranteed to be a pure decode
        continuation of what's in flight: same running set, no admissions,
        no prefills, no per-step host feedback (logprobs/penalties), and
        enough free pages that scheduling cannot preempt anything."""
        s = self.scheduler
        if s.config.num_decode_steps <= 1 or s.waiting or not s.running:
            return False
        if (
            s.spec is not None
            and s.spec_wants_sync()
            and all(spec_eligible(r.sampling_params) for r in s.running)
        ):
            # Speculative decoding runs synchronous verify passes: the
            # proposer and the verify input both need the host-current
            # last token, so while a batch that COULD draft (all
            # greedy, no penalties/logprobs) keeps drafting, every
            # dispatch resolves before the next schedule — the verify
            # pass itself is the latency hider, one HBM pass per
            # accepted window instead of per token.  Spec-impossible
            # batches (any sampled request) and draftless stretches
            # (spec_wants_sync hysteresis) keep the async dispatch
            # pipeline; the periodic probe drain re-engages spec when
            # the text turns repetitive.
            return False
        for r in s.running:
            sp = r.sampling_params
            if (
                r.is_prefill
                or sp.logprobs is not None
                or sp.repetition_penalty != 1.0
                or sp.presence_penalty != 0.0
                or sp.frequency_penalty != 0.0
            ):
                return False
            # A request whose remaining budget is fully in flight would be
            # skipped by the scheduler, shrinking the batch and breaking
            # the device carry's request order — drain first instead.
            room = (
                min(r.max_total_tokens, s.config.max_model_len)
                - r.num_tokens
                - r.num_inflight_tokens
            )
            if room <= 0:
                return False
        if self._pending:
            prev_order = [
                c.req_id for c in self._pending[-1][0].cached_requests
            ]
            if prev_order != [r.request_id for r in s.running]:
                return False
            k = s.config.num_decode_steps
            worst = sum(
                k // s.page_size + 1 for _ in s.running
            )
            if s.allocator.num_free_pages < worst:
                return False
        return True

    def _finalize_one(self) -> list[RequestOutput]:
        scheduler_output, result = self._pending.pop(0)
        if hasattr(result, "result"):  # Future
            result = result.result()
        return self._process(scheduler_output, result)

    def _drain_pending(self) -> list[RequestOutput]:
        outputs: list[RequestOutput] = []
        while self._pending:
            outputs.extend(self._finalize_one())
        return outputs

    def _finalize_done(self) -> list[RequestOutput]:
        """Finalize in-flight dispatches whose results are already
        available, WITHOUT blocking: tokens stream to the caller as each
        dispatch completes instead of surfacing only when the pipeline
        drains (r4's held-until-drain delivery was the dominant
        serving-latency artifact, VERDICT r4 weak #1)."""
        outputs: list[RequestOutput] = []
        while self._pending:
            result = self._pending[0][1]
            if hasattr(result, "done") and not result.done():
                break
            outputs.extend(self._finalize_one())
        return outputs

    def step(self) -> list[RequestOutput]:
        if self._failed:
            raise RuntimeError(self._dead_message())
        outputs: list[RequestOutput] = []
        outputs.extend(self._finalize_done())
        if self._pending and not self._pipeline_safe():
            # Reconciliation: the predicted continuation no longer holds
            # (a request finished mid-window, an admission arrived, …) —
            # drain so the next schedule sees settled state.  Deferred
            # page frees settle in the same drain.
            self.pipeline_breaks += 1
            self.metrics.record_pipeline_break()
            outputs.extend(self._drain_pending())
        scheduler_output = self._schedule()
        if self.flight_recorder.enabled:
            self._record_flight(scheduler_output)
        # Deadline sheds and preempt-to-sheds finish OUTSIDE
        # update_from_output; emit their final (partial) outputs now so
        # clients see finish_reason="timeout"/"overloaded" promptly.
        outputs.extend(self._finish_out_of_band())
        if scheduler_output.is_empty:
            # Typically every request's remaining budget is in flight:
            # block on the HEAD dispatch only, so tokens keep streaming
            # per dispatch while the tail of the pipeline drains.
            if self._pending:
                outputs.extend(self._finalize_one())
            return outputs
        if scheduler_output.decode_steps > 1 and self._pipeline_safe():
            fut = self.executor.execute_model(
                scheduler_output, non_block=True
            )
            self._pending.append((scheduler_output, fut))
            depth = self.config.scheduler_config.max_concurrent_dispatches
            while len(self._pending) > depth - 1:
                outputs.extend(self._finalize_one())
            return outputs
        outputs.extend(self._drain_pending())
        runner_output = self.executor.execute_model(scheduler_output)
        outputs.extend(self._process(scheduler_output, runner_output))
        return outputs

    def _record_flight(self, so) -> None:
        """One flight-recorder record per scheduled step (positional, in
        flight_recorder.FIELDS order — tuple pack + deque append)."""
        s = self.scheduler
        open_breakers, retry_balance = self._resilience_state()
        self.flight_recorder.record_step(
            so.step_id,
            time.time(),
            time.monotonic(),
            len(s.running),
            len(s.waiting),
            so.total_num_scheduled_tokens,
            so.decode_steps,
            len(so.new_requests),
            len(so.cached_requests),
            len(so.preempted_req_ids),
            len(so.finished_req_ids),
            sum(len(d) for d in so.draft_token_ids.values()),
            len(self._pending),
            self.pipeline_breaks,
            s.allocator.num_free_pages,
            open_breakers,
            retry_balance,
        )

    def refresh_device_telemetry(self) -> dict | None:
        """Pull one DeviceTelemetry snapshot from the reply-rank worker
        and fold it into the Prometheus instruments: compile events past
        the cursor are counted exactly once, gauges take the latest
        value.  Called on /metrics scrapes (via the AsyncLLM aux path,
        so the collective stays ordered with step dispatches) and
        directly by engine-level tests.  Best-effort: a dead executor
        just leaves the previous values standing."""
        try:
            snap = self.executor.collective_rpc(
                "get_device_telemetry",
                unique_reply_rank=self.executor.output_rank,
                # Short: this runs between step dispatches on the engine
                # thread — a slow host must cost a missed scrape, never
                # a long decode stall.
                timeout=5.0,
            )
        except Exception as e:  # noqa: BLE001 — telemetry only
            logger.debug("device-telemetry pull failed: %s", e)
            return None
        if not isinstance(snap, dict):
            return None
        # Timing histogram from the (bounded) event ring; the COUNTER
        # uses the cumulative totals below so it stays exact even when
        # more compiles happened between scrapes than the ring holds.
        for event in snap.get("compile_events", ()):
            try:
                seq, seconds = event[0], event[2]
            except (IndexError, TypeError):
                continue
            if seq > self._telemetry_seq:
                self._telemetry_seq = seq
                self.metrics.record_xla_compile_seconds(float(seconds))
        for kind, total in (snap.get("compiles") or {}).items():
            seen = self._telemetry_compiles_seen.get(kind, 0)
            if total > seen:
                self.metrics.record_xla_compiles(str(kind), total - seen)
                self._telemetry_compiles_seen[kind] = total
        self.metrics.record_device_snapshot(snap)
        return snap

    def _finish_out_of_band(self) -> list[RequestOutput]:
        """Final outputs for requests the scheduler finished outside
        update_from_output (deadline sheds, preempt-to-shed, ISSUE 8):
        partial tokens/text, finish_reason from the status, metrics and
        spans recorded like any other finish."""
        reqs = self.scheduler.take_finished_out_of_band()
        if not reqs:
            return []
        now = time.time()
        now_mono = time.monotonic()
        # QoS sheds enter the unified timeline (ISSUE 20): one event
        # per drain with the reason tally, not one per request.
        shed_reasons: dict[str, int] = {}
        for req in reqs:
            reason = FINISH_REASON.get(req.status) or "unknown"
            shed_reasons[reason] = shed_reasons.get(reason, 0) + 1
        self.metrics.events.emit(
            "qos_shed", count=len(reqs), reasons=shed_reasons
        )
        outputs: list[RequestOutput] = []
        for req in reqs:
            req.metrics.finished_time = now
            req.metrics.finished_time_mono = now_mono
            if self.tracer.enabled:
                self._record_request_spans(req, now_mono, True)
            detok = self.detokenizers.pop(req.request_id, None)
            outputs.append(self._make_output(req, detok))
            self.metrics.record_finished(
                req.metrics, FINISH_REASON.get(req.status)
            )
            if self.config.kv_transfer_config is not None:
                self.executor.kv_output_aggregator.forget(req.request_id)
        return outputs

    def _schedule(self):
        """One scheduler pass, wrapped in a per-step schedule span with
        the batch composition attached (parented to the first traced
        request in the batch; tracing off = plain call)."""
        if not self.tracer.enabled:
            return self.scheduler.schedule()
        start_wall = time.time()
        t0 = time.monotonic()
        scheduler_output = self.scheduler.schedule()
        self.tracer.record_span(
            "scheduler.schedule",
            start_wall,
            time.monotonic() - t0,
            parent=scheduler_output.trace_ctx,
            step_id=scheduler_output.step_id,
            num_new=len(scheduler_output.new_requests),
            num_cached=len(scheduler_output.cached_requests),
            num_preempted=len(scheduler_output.preempted_req_ids),
            decode_steps=scheduler_output.decode_steps,
            total_tokens=scheduler_output.total_num_scheduled_tokens,
            batch=",".join(
                f"{rid}:{n}"
                for rid, n in scheduler_output.num_scheduled_tokens.items()
            ),
        )
        return scheduler_output

    def _record_stage(
        self, req: Request, name: str, start_mono: float, end_mono: float
    ) -> None:
        """Synthesize one request-stage span from monotonic stamps.  The
        wall-clock start is derived from the arrival anchor + monotonic
        delta, so span starts are NTP-consistent with the durations."""
        m = req.metrics
        self.tracer.record_span(
            name,
            m.arrival_time + (start_mono - m.arrival_time_mono),
            max(end_mono - start_mono, 0.0),
            parent=req.trace_ctx,
            request_id=req.request_id,
        )

    def _record_request_spans(
        self, req: Request, now_mono: float, finished: bool
    ) -> None:
        """Stage spans at the two request milestones: queue+prefill when
        the first token lands, decode at finish.  A request finishing
        without ever producing a token (e.g. stop-string truncation to
        zero) still gets its earlier stages recorded at finish."""
        m = req.metrics
        first_sched = (
            m.first_scheduled_time_mono
            if m.first_scheduled_time_mono is not None
            else now_mono
        )
        if m.first_token_time_mono == now_mono and not finished:
            self._record_stage(
                req, "engine.queue", m.arrival_time_mono, first_sched
            )
            self._record_stage(req, "engine.prefill", first_sched, now_mono)
            return
        if not finished:
            return
        if m.first_token_time_mono is None:
            self._record_stage(
                req, "engine.queue", m.arrival_time_mono, first_sched
            )
            self._record_stage(req, "engine.prefill", first_sched, now_mono)
        else:
            if m.first_token_time_mono == now_mono:
                # First token and finish in the same step.
                self._record_stage(
                    req, "engine.queue", m.arrival_time_mono, first_sched
                )
                self._record_stage(
                    req, "engine.prefill", first_sched, m.first_token_time_mono
                )
            self._record_stage(
                req, "engine.decode", m.first_token_time_mono, now_mono
            )
        self.tracer.event(
            req.trace_ctx,
            "engine.finished",
            request_id=req.request_id,
            finish_reason=FINISH_REASON.get(req.status, "?"),
            num_output_tokens=req.num_output_tokens,
            # Joins traces to the per-class SLO accounting (ISSUE 12):
            # "which class were the slow traces in" becomes greppable.
            slo_class=req.sampling_params.slo_class,
        )

    def _process(
        self, scheduler_output, runner_output
    ) -> list[RequestOutput]:
        finished = self.scheduler.update_from_output(
            scheduler_output, runner_output.sampled_token_ids
        )
        now = time.time()
        now_mono = time.monotonic()
        self.metrics.record_queues(
            len(self.scheduler.running),
            len(self.scheduler.waiting),
            self.scheduler.num_waiting_tokens,
        )
        self.metrics.record_preemptions(
            self.scheduler.num_preemptions - self._preemptions_seen
        )
        self._preemptions_seen = self.scheduler.num_preemptions
        pc = (
            self.scheduler.prefix_cache_queries,
            self.scheduler.prefix_cache_hits,
            self.scheduler.prefix_cache_hits_host,
        )
        self.metrics.record_prefix_cache(
            pc[0] - self._prefix_cache_seen[0],
            pc[1] - self._prefix_cache_seen[1],
            pc[2] - self._prefix_cache_seen[2],
        )
        self._prefix_cache_seen = pc
        self.metrics.record_kv_cache_usage(self.scheduler.kv_cache_usage)
        # Tiered KV cache (ISSUE 14): tier-traffic deltas, host
        # occupancy, and the restore-stall observables on steps that
        # carried restore spans.
        slots = getattr(
            self.scheduler.allocator, "host_slots_used", 0
        )
        kt = (
            self.scheduler.kv_spill_pages,
            self.scheduler.kv_restore_pages,
            # Occupancy moves without tier traffic too (promotes and
            # subtree prunes release slots) — the gauge must follow.
            slots,
        )
        if kt != self._kv_tier_seen:
            self.metrics.record_kv_tier(
                kt[0] - self._kv_tier_seen[0],
                kt[1] - self._kv_tier_seen[1],
                host_bytes=slots * self._kv_page_bytes,
            )
            self._kv_tier_seen = kt
        if scheduler_output.kv_restore_ops:
            stall = runner_output.kv_tier_seconds
            self.metrics.record_kv_restore_seconds(stall)
            self.metrics.events.emit(
                "kv_restore",
                pages=len(scheduler_output.kv_restore_ops),
                stall_s=round(stall, 6),
                step_id=scheduler_output.step_id,
            )
            if self.tracer.enabled:
                self.tracer.record_span(
                    "engine.kv_restore",
                    now - stall,
                    stall,
                    parent=scheduler_output.trace_ctx,
                    step_id=scheduler_output.step_id,
                    pages=len(scheduler_output.kv_restore_ops),
                    spilled_pages=len(scheduler_output.kv_spill_ops),
                )
        if scheduler_output.draft_token_ids:
            sd = (
                self.scheduler.spec_drafted_tokens,
                self.scheduler.spec_accepted_tokens,
            )
            drafted = sd[0] - self._spec_seen[0]
            accepted = sd[1] - self._spec_seen[1]
            self.metrics.record_spec_decode(drafted, accepted)
            self._spec_seen = sd
            for req_id in scheduler_output.draft_token_ids:
                emitted = runner_output.sampled_token_ids.get(req_id)
                if emitted:
                    self.metrics.record_spec_acceptance_length(
                        len(emitted)
                    )
            if self.tracer.enabled:
                self.tracer.event(
                    scheduler_output.trace_ctx,
                    "engine.spec_decode",
                    step_id=scheduler_output.step_id,
                    drafted=drafted,
                    accepted=accepted,
                )

        outputs: list[RequestOutput] = []
        for req_id in scheduler_output.num_scheduled_tokens:
            req = self.scheduler.requests.get(req_id)
            if req is None:  # finished this step; look in finished list
                req = next(
                    (r for r in finished if r.request_id == req_id), None
                )
                if req is None:
                    continue
            new_tokens = runner_output.sampled_token_ids.get(req_id, [])
            # Prompt tokens count as PROCESSED (per prefill step), not on
            # first-token arrival — aborted/preempted prefills contribute
            # like vLLM's accounting.
            n_prefill = runner_output.num_prompt_tokens_processed.get(
                req_id, 0
            )
            if n_prefill:
                req.metrics.prompt_tokens_counted += n_prefill
                self.metrics.record_prompt_tokens(n_prefill)
            if new_tokens and req.metrics.first_token_time is None:
                req.metrics.first_token_time = now
                req.metrics.first_token_time_mono = now_mono
                # The final prefill chunk samples a token and reports no
                # num_prompt_tokens_processed: count the remainder here.
                rest = req.num_prompt_tokens - req.metrics.prompt_tokens_counted
                if rest > 0:
                    req.metrics.prompt_tokens_counted += rest
                    self.metrics.record_prompt_tokens(rest)
            self.metrics.record_new_tokens(
                req.metrics, len(new_tokens), now_mono
            )
            if req_id in runner_output.logprobs and req.logprobs is not None:
                lps = runner_output.logprobs[req_id]
                req.logprobs.extend(lps)
                for tok, lp in zip(new_tokens, lps):
                    req.cumulative_logprob += lp.get(tok, 0.0)

            detok = self.detokenizers.get(req_id)
            if detok is not None and new_tokens:
                detok.append(new_tokens)
                if detok.stopped_on is not None and not req.status.is_finished:
                    # Truncate tokens generated past the stop string so
                    # token_ids/logprobs/usage agree with the text.
                    keep = detok.stop_token_count
                    dropped = req.output_token_ids[keep:]
                    if dropped:
                        del req.output_token_ids[keep:]
                        if req.logprobs is not None and len(req.logprobs) > keep:
                            for tok, lp in zip(dropped, req.logprobs[keep:]):
                                req.cumulative_logprob -= lp.get(tok, 0.0)
                            del req.logprobs[keep:]
                    self.scheduler.finish_request(
                        req, RequestStatus.FINISHED_STOPPED
                    )
                    req.stop_reason = detok.stopped_on
                    finished.append(req)

            if req.status.is_finished:
                req.metrics.finished_time = now
                req.metrics.finished_time_mono = now_mono
            if self.tracer.enabled:
                self._record_request_spans(
                    req, now_mono, req.status.is_finished
                )
            outputs.append(self._make_output(req, detok))

        for req in finished:
            self.metrics.record_finished(
                req.metrics, FINISH_REASON.get(req.status)
            )
            self.detokenizers.pop(req.request_id, None)
            if self.config.kv_transfer_config is not None:
                self.executor.kv_output_aggregator.forget(req.request_id)
        return outputs

    def _make_output(
        self, req: Request, detok: IncrementalDetokenizer | None
    ) -> RequestOutput:
        finish_reason = FINISH_REASON.get(req.status)
        completion = CompletionOutput(
            index=0,
            text=detok.output_text if detok is not None else "",
            token_ids=list(req.output_token_ids),
            cumulative_logprob=(
                req.cumulative_logprob if req.logprobs is not None else None
            ),
            logprobs=req.logprobs,
            finish_reason=finish_reason,
            stop_reason=req.stop_reason,
        )
        return RequestOutput(
            request_id=req.request_id,
            prompt=req.prompt,
            prompt_token_ids=req.prompt_token_ids,
            outputs=[completion],
            finished=req.status.is_finished,
            metrics=req.metrics,
        )

    def embed(self, prompt_token_ids: list[int]) -> list[float]:
        """Pooled embedding of a prompt (/v1/embeddings)."""
        return self.executor.collective_rpc(
            "embed",
            (prompt_token_ids,),
            unique_reply_rank=self.executor.output_rank,
        )

    def score(self, prompt_token_ids: list[int]) -> list[float | None]:
        """Prompt logprobs (completions echo+logprobs)."""
        return self.executor.collective_rpc(
            "score",
            (prompt_token_ids,),
            unique_reply_rank=self.executor.output_rank,
        )

    def shutdown(self) -> None:
        self.tracer.clear_metrics_sink(self.metrics.observe_span)
        self.executor.shutdown()

    # Introspection used by the API layer.
    def get_model_config(self):
        return self.config.model_config
