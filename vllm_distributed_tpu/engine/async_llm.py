"""Async engine client: the surface the HTTP layer consumes.

The rebuild of the EngineClient protocol + AsyncLLM the reference drives
through build_async_engine_client_from_engine_args (launch.py:30-33,
395-407; SURVEY.md §2.3).  The engine's blocking step loop runs on a
dedicated thread (device work must not block the server's event loop);
results stream to per-request asyncio queues via call_soon_threadsafe.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import AsyncIterator

from vllm_distributed_tpu.config import EngineArgs, EngineConfig
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.outputs import RequestOutput
from vllm_distributed_tpu.sampling_params import SamplingParams

logger = init_logger(__name__)


class EngineDeadError(RuntimeError):
    pass


class AsyncLLM:
    def __init__(self, config: EngineConfig) -> None:
        self.engine = LLMEngine(config)
        self.config = config
        self._loop: asyncio.AbstractEventLoop | None = None
        self._queues: dict[str, asyncio.Queue] = {}
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._dead: BaseException | None = None
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._run_engine_loop, daemon=True, name="vdt-engine"
        )
        self._thread.start()

    @classmethod
    def from_engine_args(cls, engine_args: EngineArgs) -> "AsyncLLM":
        return cls(engine_args.create_engine_config())

    # ---- the background loop ----
    def _run_engine_loop(self) -> None:
        try:
            while not self._shutdown:
                if not self.engine.has_unfinished_requests():
                    self._wake.wait(timeout=0.2)
                    self._wake.clear()
                    continue
                with self._lock:
                    outputs = self.engine.step()
                if outputs and self._loop is not None:
                    self._loop.call_soon_threadsafe(
                        self._dispatch_outputs, outputs
                    )
        except BaseException as e:  # noqa: BLE001
            logger.exception("engine loop died")
            self._dead = e
            if self._loop is not None:
                self._loop.call_soon_threadsafe(self._fail_all_queues, e)

    def _dispatch_outputs(self, outputs: list[RequestOutput]) -> None:
        for out in outputs:
            q = self._queues.get(out.request_id)
            if q is not None:
                q.put_nowait(out)

    def _fail_all_queues(self, e: BaseException) -> None:
        for q in self._queues.values():
            q.put_nowait(e)

    # ---- EngineClient surface ----
    @property
    def is_running(self) -> bool:
        return self._dead is None and self._thread.is_alive()

    @property
    def errored(self) -> bool:
        return self._dead is not None

    async def check_health(self) -> None:
        if self._dead is not None:
            raise EngineDeadError(str(self._dead))

    async def generate(
        self,
        request_id: str,
        prompt: str | None = None,
        prompt_token_ids: list[int] | None = None,
        sampling_params: SamplingParams | None = None,
    ) -> AsyncIterator[RequestOutput]:
        """Feed a request and yield cumulative RequestOutputs until
        finished.  Cancellation (client disconnect) aborts the request."""
        if self._dead is not None:
            raise EngineDeadError(str(self._dead))
        self._loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        self._queues[request_id] = q
        try:
            # add_request tokenizes on this thread (cheap) but schedules on
            # the engine thread via the shared scheduler; the scheduler is
            # only mutated between steps, guarded by the engine lock.
            with self._lock:
                self.engine.add_request(
                    request_id,
                    prompt=prompt,
                    prompt_token_ids=prompt_token_ids,
                    sampling_params=sampling_params,
                )
            self._wake.set()
            while True:
                item = await q.get()
                if isinstance(item, BaseException):
                    raise EngineDeadError(str(item))
                yield item
                if item.finished:
                    return
        finally:
            self._queues.pop(request_id, None)
            with self._lock:
                self.engine.abort_request(request_id)

    async def abort(self, request_id: str) -> None:
        with self._lock:
            self.engine.abort_request(request_id)
        self._queues.pop(request_id, None)

    # Introspection for the API layer.
    def get_model_config(self):
        return self.config.model_config

    @property
    def tokenizer(self):
        return self.engine.tokenizer

    def shutdown(self) -> None:
        self._shutdown = True
        self._wake.set()
        self._thread.join(timeout=5)
        self.engine.shutdown()
