"""Async engine client: the surface the HTTP layer consumes.

The rebuild of the EngineClient protocol + AsyncLLM the reference drives
through build_async_engine_client_from_engine_args (launch.py:30-33,
395-407; SURVEY.md §2.3).  The engine's blocking step loop runs on a
dedicated thread (device work must not block the server's event loop).

The event loop NEVER takes a lock shared with the engine thread: intake
(add/abort) goes through a thread-safe command queue the engine thread
drains between steps, so a multi-second prefill can't freeze /health or
other SSE streams (ADVICE r1 #1 / VERDICT r2 weak #3).  Results stream
to per-request asyncio queues via call_soon_threadsafe.
"""

from __future__ import annotations

import asyncio
import queue as _queue
import threading
from typing import AsyncIterator

from vllm_distributed_tpu.config import EngineArgs, EngineConfig
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.outputs import RequestOutput
from vllm_distributed_tpu.sampling_params import SamplingParams

logger = init_logger(__name__)


class EngineDeadError(RuntimeError):
    """The engine can no longer serve.  ``failure`` carries the
    structured per-host attribution (HostFailure) when the death came
    from the multihost control plane, None otherwise."""

    def __init__(self, message: str, failure=None) -> None:
        super().__init__(message)
        self.failure = failure


class AsyncLLM:
    def __init__(self, config: EngineConfig) -> None:
        self.engine = LLMEngine(config)
        self.config = config
        self._loop: asyncio.AbstractEventLoop | None = None
        self._queues: dict[str, asyncio.Queue] = {}
        # Thread-safe intake: ("add", kwargs) / ("abort", request_id),
        # applied by the engine thread between steps.
        self._intake: _queue.SimpleQueue = _queue.SimpleQueue()
        self._wake = threading.Event()
        self._dead: BaseException | None = None
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._run_engine_loop, daemon=True, name="vdt-engine"
        )
        self._thread.start()

    @classmethod
    def from_engine_args(cls, engine_args: EngineArgs) -> "AsyncLLM":
        return cls(engine_args.create_engine_config())

    # ---- the background loop ----
    def _drain_intake(self) -> None:
        """Apply queued add/abort/aux commands (engine thread only)."""
        while True:
            try:
                op, payload = self._intake.get_nowait()
            except _queue.Empty:
                return
            if op == "add":
                request_id = payload["request_id"]
                try:
                    self.engine.add_request(**payload)
                except Exception as e:  # noqa: BLE001 — per-request error
                    # Surface intake errors (too-long prompt, bad params)
                    # on the request's own stream, preserving the type so
                    # the API layer can map e.g. ValueError -> 400.
                    self._to_request_queue(request_id, e)
            elif op == "aux":
                # Auxiliary device work (embed/score) runs HERE so its
                # collective dispatch is totally ordered with step
                # dispatches — on a multihost mesh, racing callers would
                # otherwise enqueue mismatched programs across hosts.
                fn, args, fut = payload
                try:
                    result = fn(*args)
                    err = None
                except Exception as e:  # noqa: BLE001
                    result, err = None, e
                if self._loop is not None:
                    self._loop.call_soon_threadsafe(
                        self._resolve_aux, fut, result, err
                    )
            else:  # "abort"
                self.engine.abort_request(payload)

    @staticmethod
    def _resolve_aux(fut, result, err) -> None:
        if fut.cancelled():
            return
        if err is not None:
            fut.set_exception(err)
        else:
            fut.set_result(result)

    async def _run_aux(self, fn, *args):
        if self._dead is not None:
            raise self._dead_error()
        loop = asyncio.get_running_loop()
        self._loop = loop
        fut = loop.create_future()
        self._intake.put(("aux", (fn, args, fut)))
        self._wake.set()
        if self._dead is not None and not fut.done():
            # Raced the engine death after its intake drain.
            raise self._dead_error()
        return await fut

    def _to_request_queue(self, request_id: str, item) -> None:
        if self._loop is None:
            return
        self._loop.call_soon_threadsafe(
            lambda: self._dispatch_item(request_id, item)
        )

    def _dispatch_item(self, request_id: str, item) -> None:
        q = self._queues.get(request_id)
        if q is not None:
            q.put_nowait(item)

    def _run_engine_loop(self) -> None:
        try:
            while not self._shutdown:
                self._drain_intake()
                if self.engine.errored:
                    # An idle deployment with a dead executor must not
                    # look healthy: heartbeat/disconnect failures are
                    # surfaced here even when no request is in flight
                    # (step() would never run to notice them).
                    raise RuntimeError(self.engine._dead_message())
                if not self.engine.has_unfinished_requests():
                    self._wake.wait(timeout=0.2)
                    self._wake.clear()
                    continue
                outputs = self.engine.step()
                if outputs and self._loop is not None:
                    self._loop.call_soon_threadsafe(
                        self._dispatch_outputs, outputs
                    )
        except BaseException as e:  # noqa: BLE001
            logger.exception("engine loop died")
            self._dead = e
            if self._loop is not None:
                self._loop.call_soon_threadsafe(
                    self._fail_all_queues, self._dead_error()
                )
            # Aux ops already queued (or racing the death) would await
            # forever — fail them too.
            while True:
                try:
                    op, payload = self._intake.get_nowait()
                except _queue.Empty:
                    break
                if op == "aux" and self._loop is not None:
                    self._loop.call_soon_threadsafe(
                        self._resolve_aux,
                        payload[2],
                        None,
                        self._dead_error(),
                    )

    def _dispatch_outputs(self, outputs: list[RequestOutput]) -> None:
        for out in outputs:
            q = self._queues.get(out.request_id)
            if q is not None:
                q.put_nowait(out)

    def _fail_all_queues(self, e: BaseException) -> None:
        for q in self._queues.values():
            q.put_nowait(e)

    def _dead_error(self) -> EngineDeadError:
        """Typed death with the structured HostFailure attached (drain
        contract: every in-flight/queued/new request gets THIS, never a
        hang)."""
        return EngineDeadError(
            str(self._dead) if self._dead is not None
            else self.engine._dead_message(),
            failure=self.failure_info,
        )

    # ---- EngineClient surface ----
    @property
    def is_running(self) -> bool:
        return self._dead is None and self._thread.is_alive()

    @property
    def errored(self) -> bool:
        return self._dead is not None or self.engine.errored

    @property
    def failure_info(self):
        """Structured HostFailure from the control plane, if any."""
        return getattr(self.engine, "failure_info", None)

    async def check_health(self) -> None:
        if self._dead is not None or self.engine.errored:
            raise self._dead_error()

    async def generate(
        self,
        request_id: str,
        prompt: str | None = None,
        prompt_token_ids: list[int] | None = None,
        sampling_params: SamplingParams | None = None,
    ) -> AsyncIterator[RequestOutput]:
        """Feed a request and yield cumulative RequestOutputs until
        finished.  Cancellation (client disconnect) aborts the request."""
        if self._dead is not None or self.engine.errored:
            raise self._dead_error()
        self._loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        self._queues[request_id] = q
        try:
            if self._dead is not None:
                # Raced the death after the check above: the fail-all
                # sweep may have already run without seeing our queue.
                raise self._dead_error()
            self._intake.put(
                (
                    "add",
                    dict(
                        request_id=request_id,
                        prompt=prompt,
                        prompt_token_ids=prompt_token_ids,
                        sampling_params=sampling_params,
                    ),
                )
            )
            self._wake.set()
            while True:
                item = await q.get()
                if isinstance(item, BaseException):
                    raise item
                yield item
                if item.finished:
                    return
        finally:
            self._queues.pop(request_id, None)
            self._intake.put(("abort", request_id))
            self._wake.set()

    async def abort(self, request_id: str) -> None:
        self._intake.put(("abort", request_id))
        self._wake.set()
        self._queues.pop(request_id, None)

    async def embed(self, prompt_token_ids: list[int]) -> list[float]:
        """Runs on the engine thread between steps (_drain_intake), so
        the aux collective is ordered with step dispatches mesh-wide."""
        return await self._run_aux(self.engine.embed, prompt_token_ids)

    async def score(self, prompt_token_ids: list[int]) -> list:
        return await self._run_aux(self.engine.score, prompt_token_ids)

    # Introspection for the API layer.
    @property
    def metrics(self):
        return self.engine.metrics

    def get_model_config(self):
        return self.config.model_config

    @property
    def tokenizer(self):
        return self.engine.tokenizer

    def shutdown(self) -> None:
        self._shutdown = True
        self._wake.set()
        self._thread.join(timeout=5)
        self.engine.shutdown()
