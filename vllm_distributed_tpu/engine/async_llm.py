"""Async engine client: the surface the HTTP layer consumes.

The rebuild of the EngineClient protocol + AsyncLLM the reference drives
through build_async_engine_client_from_engine_args (launch.py:30-33,
395-407; SURVEY.md §2.3).  The engine's blocking step loop runs on a
dedicated thread (device work must not block the server's event loop).

The event loop NEVER takes a lock shared with the engine thread: intake
(add/abort) goes through a thread-safe command queue the engine thread
drains between steps, so a multi-second prefill can't freeze /health or
other SSE streams (ADVICE r1 #1 / VERDICT r2 weak #3).  Results stream
to per-request asyncio queues via call_soon_threadsafe.

Engine death is no longer always terminal: a control-plane HostFailure
hands the engine thread to the EngineSupervisor (engine/supervisor.py),
which tears down the dead executor, waits for the agents to redial,
rebuilds the engine, and replays interrupted requests from the request
journal — in-flight generate() streams keep yielding across the blip.
Only when the restart policy is exhausted (or the death is not a
control-plane failure) does the engine reach the terminal dead state:
every queued/in-flight/new request gets a typed EngineDeadError.
"""

from __future__ import annotations

import asyncio
import json
import os
import queue as _queue
import threading
import time
from typing import AsyncIterator

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.config import EngineArgs, EngineConfig
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.engine.overload import (
    DRAIN_DRAINED,
    DRAIN_DRAINING,
    AdmissionController,
    EngineOverloadedError,
    estimate_prompt_tokens,
)
from vllm_distributed_tpu.engine.supervisor import (
    EngineSupervisor,
    JournalEntry,
)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.outputs import RequestOutput
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.tracing import get_tracer

logger = init_logger(__name__)


class EngineDeadError(RuntimeError):
    """The engine can no longer serve.  ``failure`` carries the
    structured per-host attribution (HostFailure) when the death came
    from the multihost control plane, None otherwise."""

    def __init__(self, message: str, failure=None) -> None:
        super().__init__(message)
        self.failure = failure


class EngineRecoveringError(RuntimeError):
    """The engine died but the supervisor is rebuilding it in-process.
    Distinct from EngineDeadError so /health can answer 503 with the
    RECOVERING state and a Retry-After derived from the backoff."""

    def __init__(self, message: str, failure=None, retry_after: int = 1):
        super().__init__(message)
        self.failure = failure
        self.retry_after = retry_after


class AsyncLLM:
    # How long shutdown() waits for the engine thread before concluding
    # it is wedged and skipping the device teardown it still owns.
    SHUTDOWN_JOIN_SECONDS = 5.0

    def __init__(self, config: EngineConfig) -> None:
        self.config = config
        self._loop: asyncio.AbstractEventLoop | None = None
        self._queues: dict[str, asyncio.Queue] = {}
        # Request journal (engine/supervisor.py): per live request, the
        # prompt, params, and client-visible cumulative output — what a
        # recovery replays.  Written on the event loop, snapshotted by
        # the supervisor on the engine thread after a flush barrier.
        self._journal: dict[str, JournalEntry] = {}
        # Thread-safe intake: ("add", kwargs) / ("abort", request_id) /
        # ("resume", JournalEntry) / ("aux", ...), applied by the engine
        # thread between steps.  "add" producers are bounded by the
        # AdmissionController caps; abort/aux are 1:1 with live HTTP
        # handlers, which the server's connection limits bound.
        # vdt-lint: disable=unbounded-queue — bound enforced at admission
        self._intake: _queue.SimpleQueue = _queue.SimpleQueue()
        self._wake = threading.Event()
        self._dead: BaseException | None = None
        self._shutdown = False
        # Coarse engine-thread location, for the stuck-shutdown warning:
        # boot | intake | idle | step | recovering | dead | stopped.
        self._phase = "boot"
        self.engine = LLMEngine(config)
        self.supervisor = EngineSupervisor(self)
        # Overload resilience (ISSUE 8): bounded admission + drain state.
        # Caps live in SchedulerConfig (default 0 = seed behavior).
        self._admission = AdmissionController(
            config.scheduler_config,
            retry_after=envs.VDT_OVERLOAD_RETRY_AFTER_SECONDS,
        )
        self._admission.attach_scheduler(self.engine.scheduler)
        self._drain_journal_path = envs.VDT_DRAIN_JOURNAL_PATH or None
        # Last scrape-triggered device-telemetry pull (monotonic); see
        # refresh_device_telemetry.
        self._telemetry_refreshed = float("-inf")
        # Requests journaled by a previous process's drain: re-admitted
        # (with their emitted tokens restored) when a client re-attaches
        # via generate() with the same request id.
        self._resumable: dict[str, JournalEntry] = self._load_drain_journal()
        self._thread = threading.Thread(
            target=self._run_engine_loop, daemon=True, name="vdt-engine"
        )
        self._thread.start()

    @classmethod
    def from_engine_args(cls, engine_args: EngineArgs) -> "AsyncLLM":
        return cls(engine_args.create_engine_config())

    # ---- the background loop ----
    def _drain_intake(self) -> None:
        """Apply queued add/abort/aux commands (engine thread only)."""
        while True:
            try:
                op, payload = self._intake.get_nowait()
            except _queue.Empty:
                return
            if op == "add":
                # The reservation moves from "intake-pending" to
                # scheduler state (counted there) the moment the add is
                # consumed — even on error, the tokens never reach the
                # waiting queue.
                est = payload.pop("_est_tokens", 0)
                self._admission.consumed(est, payload.pop("_est_class", None))
                request_id = payload["request_id"]
                entry = self._journal.get(request_id)
                if entry is not None:
                    # Consumed from the intake: from here on, recovery
                    # must replay this request (the op won't re-run).
                    entry.admitted = True
                try:
                    self.engine.add_request(**payload)
                except Exception as e:  # noqa: BLE001 — per-request error
                    # Surface intake errors (too-long prompt, bad params)
                    # on the request's own stream, preserving the type so
                    # the API layer can map e.g. ValueError -> 400.
                    self._to_request_queue(request_id, e)
            elif op == "resume":
                # Drain-journal replay (ISSUE 8): re-admit a request a
                # previous process drained, with its delivered tokens
                # restored as output state (preemption-resume
                # semantics, engine/supervisor.py JournalEntry).
                entry = payload
                entry.admitted = True
                try:
                    entry.replay_into(self.engine)
                except Exception as e:  # noqa: BLE001 — per-request error
                    self._to_request_queue(entry.request_id, e)
            elif op == "aux":
                # Auxiliary device work (embed/score) runs HERE so its
                # collective dispatch is totally ordered with step
                # dispatches — on a multihost mesh, racing callers would
                # otherwise enqueue mismatched programs across hosts.
                fn, args, fut = payload
                try:
                    result = fn(*args)
                    err = None
                except Exception as e:  # noqa: BLE001
                    result, err = None, e
                if self._loop is not None:
                    self._loop.call_soon_threadsafe(
                        self._resolve_aux, fut, result, err
                    )
            else:  # "abort"
                self.engine.abort_request(payload)

    @staticmethod
    def _resolve_aux(fut, result, err) -> None:
        if fut.cancelled():
            return
        if fut.done():
            return  # already failed by a concurrent sweep
        if err is not None:
            fut.set_exception(err)
        else:
            fut.set_result(result)

    async def _run_aux(self, fn, *args):
        if self._dead is not None:
            raise self._dead_error()
        loop = asyncio.get_running_loop()
        self._loop = loop
        fut = loop.create_future()
        self._intake.put(("aux", (fn, args, fut)))
        self._wake.set()
        # Death-race fix (ISSUE 4 satellite): an aux enqueued after the
        # engine thread's post-death/post-shutdown intake sweep would
        # otherwise await forever.  The terminal sweep now also runs from
        # _fail_all_queues (event-loop side), and this re-check covers
        # a put that lands after BOTH sweeps.
        if self._shutdown and not fut.done():
            raise EngineDeadError("AsyncLLM is shutting down")
        if self._dead is not None and not fut.done():
            raise self._dead_error()
        return await fut

    def _to_request_queue(self, request_id: str, item) -> None:
        if self._loop is None:
            return
        self._loop.call_soon_threadsafe(
            lambda: self._dispatch_item(request_id, item)
        )

    def _dispatch_item(self, request_id: str, item) -> None:
        q = self._queues.get(request_id)
        if q is not None:
            q.put_nowait(item)

    def _run_engine_loop(self) -> None:
        while True:
            try:
                self._serve_until_shutdown()
            except BaseException as e:  # noqa: BLE001
                logger.exception("engine loop died")
                try:
                    recovered = (
                        not self._shutdown and self.supervisor.recover(e)
                    )
                except BaseException:  # noqa: BLE001
                    # A recovery-cycle bug must still land in the
                    # terminal drain below — never a silent thread death
                    # that leaves every stream hanging.
                    logger.exception("engine recovery itself failed")
                    recovered = False
                if recovered:
                    continue  # fresh engine installed; keep serving
                self._phase = "dead"
                self._dead = e
                if self._loop is not None:
                    self._loop.call_soon_threadsafe(
                        self._fail_all_queues, self._dead_error()
                    )
                # Belt and braces: _fail_all_queues sweeps the intake on
                # the event loop, but if the loop is gone (or a put races
                # both sweeps) resolve from here too.
                self._sweep_intake(self._dead_error())
                return
            # Clean shutdown: anything still queued (aux futures in
            # particular) must not leave its caller hanging.
            self._phase = "stopped"
            self._sweep_intake(
                EngineDeadError("AsyncLLM is shutting down")
            )
            return

    def _serve_until_shutdown(self) -> None:
        while not self._shutdown:
            self._phase = "intake"
            self._drain_intake()
            if self.engine.errored:
                # An idle deployment with a dead executor must not
                # look healthy: heartbeat/disconnect failures are
                # surfaced here even when no request is in flight
                # (step() would never run to notice them).
                raise RuntimeError(self.engine._dead_message())
            if not self.engine.has_unfinished_requests():
                self._phase = "idle"
                self._wake.wait(timeout=0.2)
                self._wake.clear()
                continue
            self._phase = "step"
            outputs = self.engine.step()
            if outputs and self._loop is not None:
                self._loop.call_soon_threadsafe(
                    self._dispatch_outputs, outputs
                )

    def _sweep_intake(self, error: BaseException) -> None:
        """Fail work still sitting in the intake: aux futures (callers
        await them and nothing else will ever resolve them) and "add"
        ops (a generate() racing shutdown would otherwise await its
        queue forever — on terminal death _fail_all_queues also covers
        it, but clean shutdown has no fail-all pass)."""
        while True:
            try:
                op, payload = self._intake.get_nowait()
            except _queue.Empty:
                return
            if op == "add":
                # Release the admission reservation even when the loop
                # is gone — the counters must not leak on shutdown.
                self._admission.consumed(
                    payload.pop("_est_tokens", 0),
                    payload.pop("_est_class", None),
                )
            if self._loop is None:
                continue
            try:
                if op == "aux":
                    self._loop.call_soon_threadsafe(
                        self._resolve_aux, payload[2], None, error
                    )
                elif op == "add":
                    self._to_request_queue(payload["request_id"], error)
                elif op == "resume":
                    self._to_request_queue(payload.request_id, error)
            except RuntimeError:
                return  # event loop already closed; nobody awaits

    def _dispatch_outputs(self, outputs: list[RequestOutput]) -> None:
        for out in outputs:
            entry = self._journal.get(out.request_id)
            if entry is not None:
                # Journal what the client is about to see — what a
                # recovery would restore as output state on replay.
                entry.observe(out)
            q = self._queues.get(out.request_id)
            if q is not None:
                q.put_nowait(out)

    def _fail_all_queues(self, e: BaseException) -> None:
        for q in self._queues.values():
            q.put_nowait(e)
        # Satellite fix: sweep the intake from the event loop too — an
        # aux future enqueued after the engine thread's own post-death
        # sweep must still be resolved, never left hanging.
        self._sweep_intake(e)

    def _dead_error(self) -> EngineDeadError:
        """Typed death with the structured HostFailure attached (drain
        contract: every in-flight/queued/new request gets THIS, never a
        hang)."""
        return EngineDeadError(
            str(self._dead) if self._dead is not None
            else self.engine._dead_message(),
            failure=self.failure_info,
        )

    # ---- EngineClient surface ----
    @property
    def is_running(self) -> bool:
        return self._dead is None and self._thread.is_alive()

    @property
    def errored(self) -> bool:
        return self._dead is not None or self.engine.errored

    @property
    def failure_info(self):
        """Structured HostFailure from the control plane, if any.  After
        a failed recovery the current engine may be a half-built one, so
        fall back to the supervisor's originating failure."""
        return (
            getattr(self.engine, "failure_info", None)
            or self.supervisor.last_failure
        )

    def _recovery_pending(self) -> bool:
        """True while the supervisor is (or is about to start)
        rebuilding: the engine errored but the death will be absorbed,
        so callers should wait/503-with-Retry-After, not fail."""
        sup = self.supervisor
        if sup.recovering:
            return True
        return (
            self._dead is None
            and self.engine.errored
            and sup.can_recover(getattr(self.engine, "failure_info", None))
        )

    async def check_health(self) -> None:
        if self._dead is not None:
            raise self._dead_error()
        if self._recovery_pending():
            failure = self.failure_info
            raise EngineRecoveringError(
                "engine is recovering"
                + (f": {failure.describe()}" if failure is not None else ""),
                failure=failure,
                retry_after=self.supervisor.retry_after_seconds(),
            )
        if self.engine.errored:
            raise self._dead_error()

    def _deadline_mono(self, params: SamplingParams) -> float | None:
        """Effective deadline for journaling: the client's deadline_ms
        or the server default, anchored now (the journal mirrors what
        the engine will compute at add time)."""
        ms = params.deadline_ms
        if ms is None:
            default = self.config.scheduler_config.default_deadline_ms
            ms = default if default > 0 else None
        return time.monotonic() + ms / 1000.0 if ms is not None else None

    @property
    def _journaling_enabled(self) -> bool:
        """Journaling exists for replay: in-process recovery
        (supervisor) or cross-process drain hand-off.  With neither
        configured the per-output cumulative copies are skipped."""
        if self._drain_journal_path:
            return True
        return self.supervisor.policy.max_restarts > 0 and getattr(
            self.engine.executor, "supports_recovery", False
        )

    def check_admission(
        self,
        num_requests: int = 1,
        est_tokens: int = 0,
        prompt_token_ids: list[int] | None = None,
        slo_class: str | None = None,
    ) -> None:
        """Pure admission pre-check for the HTTP layer (no
        reservation): raises EngineOverloadedError so rejects become
        429 responses before any SSE stream opens.  generate() runs the
        authoritative reserving check."""
        try:
            self._admission.check(
                num_requests, est_tokens, prompt_token_ids, slo_class
            )
        except EngineOverloadedError as e:
            self.engine.metrics.record_rejected(e.reason)
            raise

    async def generate(
        self,
        request_id: str,
        prompt: str | None = None,
        prompt_token_ids: list[int] | None = None,
        sampling_params: SamplingParams | None = None,
        trace_ctx: tuple | None = None,
    ) -> AsyncIterator[RequestOutput]:
        """Feed a request and yield cumulative RequestOutputs until
        finished.  Cancellation (client disconnect) aborts the request.
        A request submitted while the engine is RECOVERING waits in the
        intake and is admitted by the rebuilt engine.  A request id
        journaled by a previous process's drain resumes instead: the
        journaled prompt/params are re-admitted with the already
        delivered tokens restored, and outputs stay cumulative across
        the hand-off."""
        if self._dead is not None or (
            self.engine.errored and not self._recovery_pending()
        ):
            raise self._dead_error()
        self._loop = asyncio.get_running_loop()
        # Drain-journal resume: bypass admission caps — this is
        # previously ADMITTED work being handed back (losing it would
        # violate the drain contract), not new load.
        resume_entry = self._resumable.pop(request_id, None)
        est = 0
        slo = (
            sampling_params.slo_class
            if sampling_params is not None
            else None
        )
        if resume_entry is None:
            est = estimate_prompt_tokens(prompt, prompt_token_ids)
            try:
                # Bounded admission (ISSUE 8): caps + KV watermark +
                # drain state.  Default-off knobs make this a single
                # flag read in the seed configuration.  The class rides
                # along so per-class shares (ISSUE 16) bill the right
                # bucket.
                self._admission.reserve(est, prompt_token_ids, slo)
            except EngineOverloadedError as e:
                self.engine.metrics.record_rejected(e.reason)
                get_tracer().event(
                    trace_ctx,
                    "engine.rejected",
                    request_id=request_id,
                    reason=e.reason,
                )
                raise
        # Drained by this handler's own iteration below; bounded by the
        # request's max_tokens worth of outputs.
        # vdt-lint: disable=unbounded-queue — consumer is this handler
        q: asyncio.Queue = asyncio.Queue()
        self._queues[request_id] = q
        if resume_entry is not None:
            # Keep journaling the resumed request so a later drain (or
            # recovery) can hand it off again.
            self._journal[request_id] = resume_entry
        elif self._journaling_enabled:
            params = sampling_params or SamplingParams()
            self._journal[request_id] = JournalEntry(
                request_id=request_id,
                prompt=prompt,
                prompt_token_ids=(
                    list(prompt_token_ids)
                    if prompt_token_ids is not None
                    else None
                ),
                sampling_params=params.clone(),
                trace_ctx=trace_ctx,
                deadline_mono=self._deadline_mono(params),
            )
        try:
            if self._dead is not None:
                # Raced the death after the check above: the fail-all
                # sweep may have already run without seeing our queue.
                if resume_entry is None:
                    self._admission.release(est, slo)
                raise self._dead_error()
            if resume_entry is not None:
                self._intake.put(("resume", resume_entry))
            else:
                self._intake.put(
                    (
                        "add",
                        dict(
                            request_id=request_id,
                            prompt=prompt,
                            prompt_token_ids=prompt_token_ids,
                            sampling_params=sampling_params,
                            trace_ctx=trace_ctx,
                            _est_tokens=est,
                            _est_class=slo,
                        ),
                    )
                )
            self._wake.set()
            if self._shutdown:
                # Raced shutdown(): the engine thread's final sweep may
                # have run before our put (mirror of the _run_aux
                # re-check — never leave the stream awaiting forever).
                raise EngineDeadError("AsyncLLM is shutting down")
            while True:
                item = await q.get()
                if isinstance(item, BaseException):
                    raise item
                yield item
                if item.finished:
                    return
        finally:
            # A resume takeover (api_server.internal_resume replaying
            # an id after a router crash, ISSUE 17) may have replaced
            # this handler's queue with a fresh one; tearing down here
            # would abort the successor's engine-side request.  Only
            # clean up what is still ours.
            if self._queues.get(request_id) is q:
                self._queues.pop(request_id, None)
                self._journal.pop(request_id, None)
                self._intake.put(("abort", request_id))
                self._wake.set()

    async def abort(self, request_id: str) -> None:
        self._intake.put(("abort", request_id))
        self._wake.set()
        self._queues.pop(request_id, None)
        self._journal.pop(request_id, None)

    async def intake_barrier(self) -> None:
        """Resolve once every intake op enqueued before this call has
        been applied by the engine thread.  The takeover fence for a
        replayed /internal/resume (ISSUE 17): after ``abort(rid)`` +
        ``intake_barrier()``, the engine holds no request ``rid`` and no
        stale output of the old incarnation can contaminate a successor
        — outputs dispatched before the barrier resolved found no queue
        or journal registered under the id and were dropped (output
        dispatch and barrier resolution are FIFO on the event loop)."""
        await self._run_aux(lambda: None)

    # ---- graceful drain (ISSUE 8) ----
    @property
    def draining(self) -> bool:
        return self._admission.draining

    @property
    def drain_state_name(self) -> str:
        return self._admission.drain_state_name

    def register_resumable(self, entry: JournalEntry) -> None:
        """Live-migration intake (router/): register a journal entry
        another replica (or the router's own journal) handed off, so the
        next ``generate()`` with the same request id resumes it with the
        already-delivered tokens restored as output state — the same
        preemption-resume path a drain-journal pickup takes.  Bypasses
        admission caps: migrated work was already admitted somewhere,
        and dropping it would violate the zero-lost-work contract."""
        self._resumable[entry.request_id] = entry

    def resumable_request_ids(self) -> list[str]:
        """Request ids a previous process drained into the journal; a
        router (ROADMAP item 1) re-drives each through generate() to
        finish it here."""
        return list(self._resumable)

    async def drain(self, timeout: float | None = None) -> dict:
        """Stop admission, let in-flight work finish for up to
        ``timeout`` seconds, then journal what remains so a restarted
        engine (or another replica) replays it with zero lost admitted
        work — the hand-off primitive a multi-replica router calls
        before taking this backend out of rotation (Llumnix,
        PAPERS.md).

        New requests 429 with reason="draining" from the moment this is
        called; /health reports the drain state.  Requests still live
        at the deadline are journaled to VDT_DRAIN_JOURNAL_PATH (when
        set), then their streams are terminated with a typed
        EngineOverloadedError and the engine-side work is aborted.
        Idempotent: a second call just waits again."""
        if timeout is None:
            timeout = envs.VDT_DRAIN_TIMEOUT_SECONDS
        t0 = time.monotonic()
        self._admission.begin_drain()
        self.engine.metrics.record_drain_state(DRAIN_DRAINING)
        logger.warning(
            "drain started: admission stopped, waiting up to %.1fs for "
            "%d live request(s)",
            timeout,
            len(self._queues),
        )
        deadline = t0 + timeout
        while time.monotonic() < deadline:
            if self._dead is not None:
                break
            if (
                not self.engine.has_unfinished_requests()
                and self._admission.pending()[0] == 0
                and not any(
                    not e.finished for e in self._journal.values()
                )
            ):
                break
            await asyncio.sleep(0.05)
        # Journal whatever is still live, then cut its streams.  The
        # abort sweep covers EVERY live stream (journaling may be
        # disabled); the journal covers what can be replayed.
        leftover = [
            e for e in self._journal.values() if not e.finished
        ]
        journaled = 0
        if leftover and self._drain_journal_path:
            journaled = self._write_drain_journal(leftover)
        journaled_ids = {e.request_id for e in leftover} if journaled else set()
        aborted = []
        for request_id in list(self._queues):
            aborted.append(request_id)
            self._dispatch_item(
                request_id,
                EngineOverloadedError(
                    "engine drained: request journaled for replay"
                    if request_id in journaled_ids
                    else "engine drained: request aborted",
                    reason="draining",
                    retry_after=envs.VDT_RETRY_AFTER_SECONDS,
                ),
            )
        self._admission.finish_drain()
        self.engine.metrics.record_drain_state(DRAIN_DRAINED)
        # Flight-recorder artifact for the hand-off post-mortem trail
        # (ISSUE 12): what the engine was doing up to the drain.
        self.engine.flight_recorder.dump("drain")
        result = {
            "status": "drained",
            "waited_s": round(time.monotonic() - t0, 3),
            "journaled": journaled,
            "aborted": len(aborted),
            "journal_path": (
                self._drain_journal_path if journaled else None
            ),
        }
        logger.warning("drain finished: %s", result)
        return result

    def _write_drain_journal(self, entries: list[JournalEntry]) -> int:
        """Persist unfinished requests for a future process.  Atomic
        write (tmp + rename) so a crash mid-drain never leaves a
        half-journal a restarted engine would trip over."""
        payload = {
            "version": 1,
            "requests": [e.to_dict() for e in entries],
        }
        path = self._drain_journal_path
        tmp = f"{path}.tmp"
        # vdt-lint: disable=async-blocking — drain is a shutdown path,
        # one small local write; the loop is not serving admissions.
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return len(entries)

    def _load_drain_journal(self) -> dict[str, JournalEntry]:
        """Boot-time pickup of a previous process's drain journal.  The
        file is renamed away immediately so a crash loop can't replay
        the same work twice."""
        path = self._drain_journal_path
        if not path or not os.path.exists(path):
            return {}
        try:
            with open(path) as f:
                payload = json.load(f)
            os.replace(path, f"{path}.consumed")
        except (OSError, ValueError) as e:
            logger.error("drain journal %s unreadable: %s", path, e)
            return {}
        entries = {}
        for item in payload.get("requests", ()):
            try:
                entry = JournalEntry.from_dict(item)
            except (KeyError, TypeError, ValueError) as e:
                logger.error(
                    "drain journal entry %r malformed: %s",
                    item.get("request_id", "?"),
                    e,
                )
                continue
            entries[entry.request_id] = entry
        if entries:
            logger.warning(
                "loaded drain journal %s: %d request(s) resumable via "
                "generate() with the same request id",
                path,
                len(entries),
            )
        return entries

    async def embed(self, prompt_token_ids: list[int]) -> list[float]:
        """Runs on the engine thread between steps (_drain_intake), so
        the aux collective is ordered with step dispatches mesh-wide."""
        return await self._run_aux(
            lambda ids: self.engine.embed(ids), prompt_token_ids
        )

    # Scrape-triggered telemetry pulls are coalesced to this interval:
    # the aux RPC runs on the engine thread between steps, and the
    # /metrics endpoint is unauthenticated — without a floor, a scrape
    # storm (or several scrapers: Prometheus + the router's merged
    # view) would stall token generation behind back-to-back RPCs.
    TELEMETRY_MIN_INTERVAL_SECONDS = 2.0

    async def refresh_device_telemetry(self) -> dict | None:
        """Pull worker XLA/HBM telemetry into the metrics (ISSUE 12).
        Rides the aux path so the collective is ordered with step
        dispatches; /metrics calls this best-effort per scrape, rate-
        limited so concurrent/frequent scrapers coalesce onto one pull
        per interval (the skipped ones serve the last-pulled values)."""
        now = time.monotonic()
        if (
            now - self._telemetry_refreshed
            < self.TELEMETRY_MIN_INTERVAL_SECONDS
        ):
            return None
        # Stamp BEFORE awaiting: scrapers arriving while the pull is in
        # flight skip instead of queueing their own RPCs.
        self._telemetry_refreshed = now
        return await self._run_aux(
            lambda: self.engine.refresh_device_telemetry()
        )

    async def score(self, prompt_token_ids: list[int]) -> list:
        return await self._run_aux(
            lambda ids: self.engine.score(ids), prompt_token_ids
        )

    # ---- KV-page hand-off (disaggregated prefill, ISSUE 15) ----
    # All ride the aux path: allocator mutation happens on the engine
    # thread (serialized with the scheduler) and the export/import
    # collectives stay ordered with step dispatches mesh-wide.
    async def kv_export(
        self, handle: str, layer_start: int, layer_count: int
    ) -> dict:
        return await self._run_aux(
            lambda: self.engine.kv_transfer.export(
                handle, layer_start, layer_count
            )
        )

    async def kv_release(self, handle: str) -> bool:
        return await self._run_aux(
            lambda: self.engine.kv_transfer.release(handle)
        )

    async def kv_import_begin(
        self, token_ids: list[int], resume_from: str | None = None
    ) -> dict:
        return await self._run_aux(
            lambda: self.engine.kv_transfer.begin_import(
                token_ids, resume_from=resume_from
            )
        )

    async def kv_import_chunk(
        self, transfer_id: str, layers: list[dict]
    ) -> dict:
        return await self._run_aux(
            lambda: self.engine.kv_transfer.apply_chunk(
                transfer_id, layers
            )
        )

    async def kv_import_commit(self, transfer_id: str) -> dict:
        return await self._run_aux(
            lambda: self.engine.kv_transfer.commit_import(transfer_id)
        )

    async def kv_import_abort(self, transfer_id: str) -> bool:
        return await self._run_aux(
            lambda: self.engine.kv_transfer.abort_import(transfer_id)
        )

    # Introspection for the API layer.
    @property
    def metrics(self):
        return self.engine.metrics

    def get_model_config(self):
        return self.config.model_config

    @property
    def tokenizer(self):
        return self.engine.tokenizer

    def shutdown(self) -> None:
        self._shutdown = True
        self._wake.set()
        self.supervisor.interrupt()
        self._thread.join(timeout=self.SHUTDOWN_JOIN_SECONDS)
        if self._thread.is_alive():
            # Satellite fix: a failed join used to fall through into
            # engine.shutdown(), racing the stuck thread for the device.
            logger.warning(
                "engine thread did not exit within %.0fs (stuck in phase "
                "%r); skipping engine teardown — the stuck thread still "
                "owns the device",
                self.SHUTDOWN_JOIN_SECONDS,
                self._phase,
            )
            return
        self.engine.shutdown()
