"""QoS class registry: the control half of SLO-class serving (ISSUE 16).

PR 12 gave every request an ``slo_class`` and measured per-class goodput
(engine/slo.py); nothing *acted* on it — a bulk batch job and an
interactive chat request were admitted, scheduled, preempted, placed,
and scaled identically.  This registry is the shared vocabulary the
control loops key on:

- **admission** (engine/overload.py): per-class guaranteed-minimum
  shares of the bounded-admission caps, with work-conserving borrowing —
  under overload the 429s land on classes over their share first
  instead of FIFO arrival order;
- **scheduling** (engine/scheduler.py): class priority orders waiting
  admission and picks preemption victims (lowest class evicted first),
  and the preemption weight scales the preempt-to-shed budget;
- **placement/scaling** (router/qos.py): the same parsed registry
  drives per-class replica placement and the per-class goodput
  autoscale trigger.

Configured via ``VDT_QOS_CLASSES`` / ``--qos-classes`` with one entry
per class, ``name:priority[:share[:weight]]``, comma-separated — e.g.
``interactive:10:0.5,default:0:0.3,batch:-10:0:2.0``.  Empty (the
default) leaves the registry DISABLED: a single "default" class and
every hook a no-op, so seed scheduling is bit-identical.

Class names pass through :func:`engine.slo.sanitize_class` and the
registry refuses more than :data:`engine.slo.MAX_CLASSES` entries, so
every label a QoS control loop can emit already satisfies the PR 12
metrics cardinality cap.
"""

from __future__ import annotations

from dataclasses import dataclass

from vllm_distributed_tpu.engine.slo import (
    DEFAULT_CLASS,
    MAX_CLASSES,
    sanitize_class,
)


@dataclass(frozen=True)
class QosClass:
    """One SLO class's control parameters."""

    name: str
    # Strict ordering: higher admits first, preempts last.  Ties keep
    # FIFO arrival order, so equal-priority classes behave like today.
    priority: int = 0
    # Guaranteed-minimum fraction of each bounded-admission cap
    # (max_waiting_requests / max_queued_tokens).  0 = no guarantee:
    # the class admits only from spare (borrowed) capacity.
    admission_share: float = 0.0
    # Scales the preempt-to-shed budget (VDT_PREEMPT_SHED_THRESHOLD):
    # a 0.5-weight class is shed after half the preemptions, a
    # 2.0-weight class tolerates twice as many.  1.0 = unchanged.
    preemption_weight: float = 1.0


_DEFAULT = QosClass(name=DEFAULT_CLASS)


def parse_qos_classes(spec: str) -> dict[str, QosClass]:
    """Parse a ``name:priority[:share[:weight]]`` comma list.

    Raises ValueError on malformed entries, duplicate names, shares
    outside [0, 1], shares summing above 1 (guarantees must be
    satisfiable simultaneously), non-positive weights, or more than
    MAX_CLASSES entries — config errors surface at boot, not as silent
    misallocation under overload.
    """
    classes: dict[str, QosClass] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2 or len(parts) > 4:
            raise ValueError(
                f"QoS class entry {entry!r} is not "
                "name:priority[:share[:weight]]"
            )
        name = sanitize_class(parts[0])
        if name in classes:
            raise ValueError(f"duplicate QoS class {name!r}")
        try:
            priority = int(parts[1])
            share = float(parts[2]) if len(parts) > 2 else 0.0
            weight = float(parts[3]) if len(parts) > 3 else 1.0
        except ValueError as e:
            raise ValueError(
                f"QoS class entry {entry!r}: {e}"
            ) from None
        if not 0.0 <= share <= 1.0:
            raise ValueError(
                f"QoS class {name!r} admission share {share} is "
                "outside [0, 1]"
            )
        if weight <= 0.0:
            raise ValueError(
                f"QoS class {name!r} preemption weight {weight} must "
                "be positive"
            )
        classes[name] = QosClass(
            name=name,
            priority=priority,
            admission_share=share,
            preemption_weight=weight,
        )
    if len(classes) > MAX_CLASSES:
        raise ValueError(
            f"{len(classes)} QoS classes exceed the metrics cardinality "
            f"cap of {MAX_CLASSES}"
        )
    total_share = sum(c.admission_share for c in classes.values())
    if total_share > 1.0 + 1e-9:
        raise ValueError(
            f"QoS admission shares sum to {total_share:.3f} > 1: the "
            "guaranteed minimums cannot all be honored at once"
        )
    return classes


class QosRegistry:
    """Immutable class table with a default-class fallback.

    ``enabled`` is False when built from an empty spec: every consumer
    guards its QoS branch on it, so the default configuration runs the
    exact seed code paths.
    """

    def __init__(self, classes: dict[str, QosClass] | None = None) -> None:
        self.classes: dict[str, QosClass] = dict(classes or {})
        self.enabled = bool(self.classes)
        # Unknown/absent classes get the configured "default" entry's
        # parameters when one exists, else the neutral built-in.
        self.default = self.classes.get(DEFAULT_CLASS, _DEFAULT)

    @classmethod
    def parse(cls, spec: str | None) -> QosRegistry:
        return cls(parse_qos_classes(spec or ""))

    def resolve(self, slo_class: str | None) -> QosClass:
        """Class parameters for a request's (raw) slo_class.  Unknown
        names fold into the default entry — one bucket, so request-
        supplied strings can never grow the control plane's keyspace
        past the registry (the same cap discipline as slo.resolve)."""
        if not self.enabled:
            return self.default
        return self.classes.get(sanitize_class(slo_class), self.default)

    def class_names(self) -> list[str]:
        """Registered names, highest priority first (placement order)."""
        return sorted(
            self.classes,
            key=lambda n: (-self.classes[n].priority, n),
        )

    def min_priority(self) -> int:
        if not self.classes:
            return 0
        return min(c.priority for c in self.classes.values())
