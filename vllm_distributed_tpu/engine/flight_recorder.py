"""Always-on engine flight recorder (ISSUE 12 tentpole, part 3).

A bounded ring of per-step records — batch composition, queue depths,
pipeline/spec state, KV headroom — cheap enough to leave on in
production (one tuple append per step, no strings, no allocation beyond
the tuple), so that when something goes wrong the **last N steps before
the incident are already captured**.  The ring is dumped automatically
to a JSON artifact on ``HostFailure`` (engine death), at the start of a
supervisor recovery cycle, and after a graceful drain; on demand it is
served by ``GET /debug/flightrecorder`` (``?dump=1`` writes the
artifact too).

Knobs: ``VDT_FLIGHT_RECORDER_SIZE`` (steps kept; 0 disables),
``VDT_FLIGHT_RECORDER_DIR`` (artifact directory, per-host).  Artifacts
are pruned to the newest ``_KEEP_DUMPS`` so a crash loop cannot fill
the disk.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

# One record per engine step, stored as a plain tuple in FIELD order
# (allocation-lean: no per-step dict); snapshot() re-zips to dicts.
FIELDS = (
    "step_id",
    "t_wall",
    "t_mono",
    "num_running",
    "num_waiting",
    "scheduled_tokens",
    "decode_steps",
    "num_new",
    "num_cached",
    "num_preempted",
    "num_finished",
    "drafted",
    "pending_dispatches",
    "pipeline_breaks",
    "kv_free_pages",
    # Data-plane health at the moment of the step (ISSUE 20 satellite):
    # router-resilience state from the registered provider.  0 / -1.0
    # when no ResilienceManager shares the process (the normal remote
    # deployment; in-process harnesses wire one via
    # set_resilience_provider).
    "open_breakers",
    "retry_budget_balance",
)

_KEEP_DUMPS = 16

# Process-wide resilience probe (ISSUE 20 satellite): a callable
# returning (open_breaker_count, retry_budget_balance).  The router's
# ResilienceManager registers itself when it shares the process with an
# engine (chaos harnesses, single-process deployments); otherwise every
# step records the "no data-plane state visible" sentinel (0, -1.0).
_resilience_probe = None


def set_resilience_provider(probe) -> None:
    """Install (or clear, with None) the (open_breakers,
    retry_budget_balance) provider sampled on every recorded step."""
    global _resilience_probe
    _resilience_probe = probe


def resilience_state() -> tuple[int, float]:
    probe = _resilience_probe
    if probe is None:
        return 0, -1.0
    try:
        return probe()
    except Exception:  # noqa: BLE001 — telemetry never takes the engine down
        return 0, -1.0


def default_dump_dir() -> str:
    import tempfile

    return os.path.join(tempfile.gettempdir(), "vdt-flightrecorder")


class FlightRecorder:
    """Bounded per-step ring + JSON dump.  Engine-thread writer, any
    thread may snapshot (tuple append/iteration are GIL-atomic)."""

    def __init__(
        self, size: int | None = None, dump_dir: str | None = None
    ) -> None:
        if size is None or dump_dir is None:
            from vllm_distributed_tpu import envs

            if size is None:
                size = envs.VDT_FLIGHT_RECORDER_SIZE
            if dump_dir is None:
                dump_dir = (
                    envs.VDT_FLIGHT_RECORDER_DIR or default_dump_dir()
                )
        self.enabled = size > 0
        self.dump_dir = dump_dir
        self._ring: deque[tuple] = deque(maxlen=max(size, 1))
        # Pre-sentinel internal marker ring (interleaved into dumps);
        # the unified timeline gets a structured event per DUMP via the
        # attached SentinelLog, not per marker.
        self._events: deque[tuple] = deque(maxlen=64)  # (t_wall, name, detail)
        # The engine's SentinelLog (ISSUE 20), attached by LLMEngine so
        # every dump lands in the unified timeline.
        self.sentinel = None

    def record_step(self, *values) -> None:
        """Append one step record (positional, in FIELD order — the hot
        path stays a tuple pack + deque append)."""
        if self.enabled:
            self._ring.append(values)

    def record_event(self, name: str, detail: str = "") -> None:
        """Out-of-band marker (failure, recovery, drain) interleaved
        with the step ring by timestamp in the dump."""
        if self.enabled:
            # vdt-lint: disable=sentinel-emitter — the recorder's own marker ring feeds dumps, not /debug/events; the timeline gets one event per dump
            self._events.append((time.time(), name, detail))

    def snapshot(self) -> dict:
        return {
            "version": 1,
            "fields": list(FIELDS),
            "steps": [list(r) for r in list(self._ring)],
            "events": [
                {"t_wall": t, "name": n, "detail": d}
                for t, n, d in list(self._events)
            ],
        }

    def dump(self, reason: str, extra: dict | None = None) -> str | None:
        """Write the ring to a JSON artifact; returns the path (or None
        when disabled/unwritable — telemetry never takes the engine
        down).  Old artifacts are pruned to the newest _KEEP_DUMPS."""
        if not self.enabled:
            return None
        self.record_event(f"dump:{reason}")
        payload = self.snapshot()
        payload["reason"] = reason
        payload["t_dump"] = time.time()
        payload["pid"] = os.getpid()
        if extra:
            payload["extra"] = extra
        name = (
            f"flightrecorder-{reason}-{os.getpid()}-"
            f"{int(time.time() * 1000)}.json"
        )
        path = os.path.join(self.dump_dir, name)
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
            self._prune()
        except OSError as e:
            logger.warning("flight-recorder dump to %s failed: %s", path, e)
            return None
        logger.warning(
            "flight recorder dumped %d step record(s) to %s (%s)",
            len(payload["steps"]),
            path,
            reason,
        )
        if self.sentinel is not None:
            self.sentinel.emit(
                "flight_recorder_dump",
                reason=reason,
                path=path,
                steps=len(payload["steps"]),
            )
        return path

    def _prune(self) -> None:
        try:
            # Scoped to THIS process's dumps (filenames carry the pid)
            # and ordered by mtime: co-hosted replicas sharing the
            # default directory must never delete each other's incident
            # artifacts, and a lexicographic order (reason/pid first)
            # could delete the current incident's dump while keeping
            # stale ones.
            marker = f"-{os.getpid()}-"
            dumps = sorted(
                (
                    os.path.join(self.dump_dir, f)
                    for f in os.listdir(self.dump_dir)
                    if f.startswith("flightrecorder-")
                    and f.endswith(".json")
                    and marker in f
                ),
                key=os.path.getmtime,
            )
            for stale in dumps[:-_KEEP_DUMPS]:
                os.unlink(stale)
        except OSError as e:  # best-effort hygiene only
            logger.debug("flight-recorder prune failed: %s", e)
