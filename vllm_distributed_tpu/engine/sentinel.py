"""Fleet sentinel primitives (ISSUE 20): the unified event timeline
and multi-window SLO burn-rate math shared by the engine and router.

Three pieces live here because both processes need them:

* ``EVENT_KINDS`` — the registered vocabulary of timeline event kinds.
  Every emission goes through :meth:`SentinelLog.emit`, which rejects
  unregistered kinds; vdt-lint rule VDT011 enforces the same contract
  statically (no ad-hoc appends to event rings, literal kinds must be
  registered here).
* :class:`SentinelLog` — a bounded, monotonic-stamped structured event
  log.  Each event carries ``ts_mono`` (in-process causal order),
  ``ts_wall`` (cross-replica merge, corrected by the router's
  heartbeat-RTT clock offsets), a per-log ``seq`` (total-order
  tiebreak), ``source``, ``kind``, optional ``replica_id``/``trace_id``
  and free-form ``attrs``.  Served per-replica at ``GET /debug/events``
  and merged fleet-wide at ``GET /router/timeline``.
* :class:`BurnRateTracker` — SRE-style multi-window SLO burn rate over
  the per-class attainment counters (ISSUE 12).  Burn rate is
  ``error_rate / (1 - objective)``; an alert fires only when EVERY
  window (5m and 1h by default) exceeds the threshold, which is the
  standard fast-burn/slow-burn pairing: the short window gives fast
  detection, the long window keeps one bad minute from paging.

Everything is observe-only and default-on-but-inert: with no SLO
targets configured the burn tracker sees goodput == requests and burns
0; with nothing emitting, the log is empty.  ``VDT_SENTINEL_EVENTS_SIZE=0``
disables event collection entirely (seed behavior).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

# ---------------------------------------------------------------------------
# Registered event kinds.  VDT011 checks literal kinds passed to
# ``.emit("...")`` against this set; SentinelLog.emit re-checks at
# runtime so dynamically-built kinds can't sneak past the linter.
# ---------------------------------------------------------------------------
EVENT_KINDS = frozenset({
    # ---- engine-side emitters ----
    "flight_recorder_dump",   # flight recorder wrote a post-mortem artifact
    "recovery_begin",         # supervisor started an in-process rebuild
    "recovery_attempt",       # one rebuild attempt (attrs: attempt)
    "recovery_success",       # engine recovered
    "recovery_failed",        # supervisor gave up (engine dead)
    "qos_shed",               # scheduler shed expired/overload requests
    "kv_handoff",             # prefill->decode KV hand-off outcome
    "kv_restore",             # decode-side KV restore outcome
    # ---- router-side emitters ----
    "breaker_transition",     # circuit breaker state change (attrs: state)
    "autoscale_decision",     # autoscaler chose a target
    "wal_compaction",         # router WAL rotated onto a fresh snapshot
    "replica_state",          # pool probe observed a state transition
    "router_handoff",         # disaggregated prefill hand-off outcome
    # ---- alerts (also appended to the bounded /router/alerts feed) ----
    "alert_slo_burn",         # multi-window burn-rate breach for a class
    "alert_replica_degraded", # anomaly score / breaker singled a replica out
    "alert_replica_unreachable",  # healthy replica stopped answering probes
    # ---- fleet lifecycle (ReplicaManager.record_event forwards) ----
    "spawn", "crash", "adopt", "adopt_dead", "adopt_verified",
    "adopt_identity_mismatch", "adopt_verify_timeout", "ready",
    "drain", "drained", "drain_failed", "abort_warmup", "stopped",
    "scale", "scale_role", "restart_budget_exhausted", "warmup_failed",
    "shutdown_drain", "recycle_recommended",
})


class SentinelLog:
    """Bounded structured event log, one per component (engine metrics
    object, router state).  Thread-safe: engines emit from the engine
    thread while ``/debug/events`` reads from the event loop.
    """

    def __init__(
        self,
        source: str,
        maxlen: int | None = None,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
    ) -> None:
        if maxlen is None:
            from vllm_distributed_tpu import envs

            maxlen = envs.VDT_SENTINEL_EVENTS_SIZE
        self.source = source
        self.enabled = maxlen > 0
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self._seq = 0
        self._events: deque[dict] = deque(maxlen=max(maxlen, 1))

    def emit(
        self,
        kind: str,
        replica_id: str = "",
        trace_id: str = "",
        **attrs,
    ) -> dict | None:
        """Append one event; returns it (or None when disabled)."""
        if kind not in EVENT_KINDS:
            raise ValueError(
                f"unregistered sentinel event kind {kind!r} — add it to "
                "engine/sentinel.py EVENT_KINDS (VDT011)"
            )
        if not self.enabled:
            return None
        event = {
            "ts_mono": round(self._clock(), 6),
            "ts_wall": round(self._wall(), 6),
            "source": self.source,
            "kind": kind,
        }
        if replica_id:
            event["replica_id"] = replica_id
        if trace_id:
            event["trace_id"] = trace_id
        if attrs:
            event["attrs"] = attrs
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
        return event

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(ev) for ev in self._events]

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


# ---------------------------------------------------------------------------
# Multi-window SLO burn rate.
# ---------------------------------------------------------------------------

#: Paired alerting windows: (label, seconds).  An alert requires EVERY
#: window to burn past the threshold simultaneously.
BURN_WINDOWS: tuple[tuple[str, float], ...] = (("5m", 300.0), ("1h", 3600.0))

# Samples closer together than this coalesce in place, bounding the
# per-class deque to ~window/seconds entries regardless of request rate.
_SAMPLE_COALESCE_S = 1.0


class BurnRateTracker:
    """Burn rate over cumulative per-class (requests, goodput) counters.

    ``observe`` takes the *cumulative* totals (monotone non-decreasing;
    the engine feeds its own SLO accounting, the router feeds the
    fleet-summed scrape) and keeps a bounded trail of samples per class.
    The burn over a window is::

        error_rate = (d_requests - d_goodput) / d_requests
        burn       = error_rate / (1 - objective)

    where the deltas span from the newest sample at-or-before the
    window start (fallback: oldest retained) to now.  burn == 1.0 means
    the error budget is being spent exactly at the sustainable rate;
    burn >= threshold on every window simultaneously fires the alert
    (rising-edge: one alert per excursion per class).
    """

    def __init__(
        self,
        objective: float | None = None,
        threshold: float | None = None,
        windows: tuple[tuple[str, float], ...] = BURN_WINDOWS,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        from vllm_distributed_tpu import envs

        if objective is None:
            objective = envs.VDT_SLO_OBJECTIVE
        if threshold is None:
            threshold = envs.VDT_SENTINEL_BURN_THRESHOLD
        # Clamp away degenerate objectives (1.0 would divide by zero).
        self.objective = min(max(objective, 0.0), 0.9999)
        self.threshold = threshold
        self.windows = windows
        self._clock = clock
        self._lock = threading.Lock()
        self._max_window = max(sec for _, sec in windows)
        self._samples: dict[str, deque[tuple[float, int, int]]] = {}
        self._alerting: set[str] = set()
        self.peak: float = 0.0  # high-water fleet/replica burn (any window)

    def observe(
        self,
        cls: str,
        requests: int,
        goodput: int,
        now: float | None = None,
    ) -> list[dict]:
        """Record cumulative totals for ``cls``; returns newly-fired
        alert descriptors (empty on no edge)."""
        if now is None:
            now = self._clock()
        fired: list[dict] = []
        with self._lock:
            trail = self._samples.get(cls)
            if trail is None:
                # vdt-lint: disable=unbounded-queue — coalescing (1 sample/s) plus the horizon prune below bound this to ~max_window entries
                trail = self._samples[cls] = deque()
            sample = (now, int(requests), int(goodput))
            if trail and now - trail[-1][0] < _SAMPLE_COALESCE_S:
                trail[-1] = sample
            else:
                trail.append(sample)
            horizon = now - self._max_window - 2 * _SAMPLE_COALESCE_S
            # Keep one sample beyond the horizon as the long-window base.
            while len(trail) > 1 and trail[1][0] <= horizon:
                trail.popleft()
            rates = self._burn_rates_locked(cls, now)
            if rates:
                self.peak = max(self.peak, max(rates.values()))
            breaching = bool(rates) and all(
                r >= self.threshold for r in rates.values()
            )
            if breaching and cls not in self._alerting:
                self._alerting.add(cls)
                fired.append({
                    "slo_class": cls,
                    "threshold": self.threshold,
                    "burn": {w: round(r, 3) for w, r in rates.items()},
                })
            elif not breaching:
                self._alerting.discard(cls)
        return fired

    def _burn_rates_locked(self, cls: str, now: float) -> dict[str, float]:
        trail = self._samples.get(cls)
        if not trail:
            return {}
        _, cur_req, cur_good = trail[-1]
        rates: dict[str, float] = {}
        for label, seconds in self.windows:
            start = now - seconds
            base = trail[0]
            for sample in trail:
                if sample[0] <= start:
                    base = sample
                else:
                    break
            d_req = cur_req - base[1]
            d_good = cur_good - base[2]
            if d_req <= 0:
                rates[label] = 0.0
                continue
            error_rate = max(cur_req - base[1] - d_good, 0) / d_req
            rates[label] = error_rate / (1.0 - self.objective)
        return rates

    def burn_rates(self, cls: str, now: float | None = None) -> dict[str, float]:
        """Current per-window burn rates for one class (empty if the
        class has never been observed)."""
        if now is None:
            now = self._clock()
        with self._lock:
            return self._burn_rates_locked(cls, now)

    def classes(self) -> list[str]:
        with self._lock:
            return sorted(self._samples)

    def snapshot(self, now: float | None = None) -> dict[str, dict[str, float]]:
        """{class: {window: burn}} for every observed class."""
        if now is None:
            now = self._clock()
        with self._lock:
            return {
                cls: self._burn_rates_locked(cls, now)
                for cls in sorted(self._samples)
            }
