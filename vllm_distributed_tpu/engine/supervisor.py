"""Supervised in-process engine recovery (ISSUE 4 tentpole).

PR 2 made failure *detection* first-class; this module makes recovery
in-process.  Instead of one transient host blip permanently killing the
engine until an external supervisor (compose/systemd) restarts the whole
server process, the ``EngineSupervisor`` turns a fatal ``HostFailure``
into a bounded recovery cycle, run on the engine thread itself:

1. tear down the dead executor (synchronous — the listening port must be
   released so the rebuilt executor can re-listen on it);
2. back off (exponential, capped), letting the agents redial: a deployed
   agent exits on disconnect by design and its own supervisor restarts
   it, so the rebuilt ``MultiHostExecutor`` blocks in its constructor
   until ``num_hosts`` slots refill — the same boot path as cold start,
   but warm (AOT artifact cache + XLA disk cache skip trace/compile);
3. rebuild ``LLMEngine`` (reusing the ``EngineMetrics`` instance so
   Prometheus counters span restarts);
4. **replay** interrupted work from the request journal as a synthetic
   preemption-resume: each live request is re-admitted with its original
   prompt, the already-delivered tokens restored as OUTPUT tokens, and
   ``resume_target`` covering them — the same recompute path a
   preempted request takes, so the prompt/output boundary (penalties,
   stop strings, EOS, token budgets) is preserved exactly, the client's
   SSE stream continues across the blip without observing an error, and
   greedy outputs are bit-identical to an uninterrupted run.

Recovery is bounded by a restart policy (``VDT_MAX_ENGINE_RESTARTS``
within ``VDT_CRASH_LOOP_WINDOW_SECONDS``); exhausting it falls back to
the pre-supervisor terminal-death behavior (typed ``EngineDeadError``,
503 with attribution).  Only control-plane deaths (a recorded
``HostFailure``) are recovered — an engine bug would just crash-loop.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.engine.request import RequestStatus
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.outputs import CompletionOutput, RequestOutput
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.tracing import get_tracer

logger = init_logger(__name__)


def _timeout_output(entry: "JournalEntry", engine) -> RequestOutput:
    """The finished output an expired journal entry's client receives
    instead of a replay: whatever was already delivered, closed with
    finish_reason="timeout".  Text is re-decoded whole from the emitted
    tokens (the journal keeps tokens, not text) — best-effort parity
    with the in-engine timeout path's partial text for non-streaming
    clients; streaming clients already received the incremental text."""
    text = ""
    tokenizer = getattr(engine, "tokenizer", None)
    if tokenizer is not None and entry.emitted_token_ids:
        try:
            text = tokenizer.decode(entry.emitted_token_ids)
        except Exception:  # noqa: BLE001 — text is best-effort here
            logger.exception(
                "decoding expired entry %s failed", entry.request_id
            )
    return RequestOutput(
        request_id=entry.request_id,
        prompt=entry.prompt,
        prompt_token_ids=list(entry.prompt_token_ids or ()),
        outputs=[
            CompletionOutput(
                index=0,
                text=text,
                token_ids=list(entry.emitted_token_ids),
                finish_reason="timeout",
            )
        ],
        finished=True,
    )


@dataclass
class JournalEntry:
    """What AsyncLLM remembers about one live request: enough to
    re-admit it after an engine rebuild with the already-delivered
    tokens restored as output state (preemption-resume semantics)."""

    request_id: str
    prompt: str | None
    prompt_token_ids: list[int] | None
    sampling_params: SamplingParams
    # Client-visible cumulative state, updated on every dispatched
    # output (event-loop side).
    emitted_token_ids: list[int] = field(default_factory=list)
    emitted_logprobs: list[dict[int, float]] | None = None
    emitted_cumulative_logprob: float = 0.0
    finished: bool = False
    # Set by the engine thread when the "add" op is consumed from the
    # intake.  Replay only covers admitted requests: a request whose add
    # is still queued reaches the rebuilt engine through the intake
    # drain, and replaying it too would admit it twice.
    admitted: bool = False
    replays: int = 0
    # Root trace context (tracing.py): the replayed request keeps
    # tracing into the same trace, and the replay itself is an event.
    trace_ctx: tuple | None = None
    # Monotonic deadline mirrored from the engine's (ISSUE 8): an
    # already-expired request is never replayed — the supervisor
    # synthesizes its timeout finish instead of re-prefilling work the
    # client has given up on.  Not persisted across processes
    # (monotonic clocks don't transfer); drain-journal resumes get a
    # fresh deadline from the new engine's default.
    deadline_mono: float | None = None

    def observe(self, out: RequestOutput) -> None:
        """Record one cumulative output about to be handed to the
        client.  Event-loop only.  Replayed requests need no splicing:
        the rebuilt engine's outputs are cumulative across the blip
        because the emitted tokens are restored as output tokens.

        Outputs are cumulative, so only the delta is appended — a full
        copy per output would make journaling O(n^2) over a request's
        lifetime, on the event loop."""
        comp = out.outputs[0]
        n = len(self.emitted_token_ids)
        if len(comp.token_ids) < n:
            # Stop-string truncation shrank the output; resync.
            self.emitted_token_ids = list(comp.token_ids)
        else:
            self.emitted_token_ids.extend(comp.token_ids[n:])
        if comp.logprobs is not None:
            if (
                self.emitted_logprobs is None
                or len(comp.logprobs) < len(self.emitted_logprobs)
            ):
                self.emitted_logprobs = list(comp.logprobs)
            else:
                self.emitted_logprobs.extend(
                    comp.logprobs[len(self.emitted_logprobs):]
                )
            self.emitted_cumulative_logprob = comp.cumulative_logprob or 0.0
        self.finished = out.finished

    def replay_into(self, engine) -> None:
        """Re-admit this request on a rebuilt engine as a synthetic
        preemption-resume: original prompt and params, emitted tokens
        restored as OUTPUT tokens, ``resume_target`` covering them.  The
        scheduler then re-prefills prompt+outputs exactly like a
        preempted request, preserving the prompt/output boundary —
        penalties, stop strings (including ones spanning the blip), EOS
        and token budgets behave as in an uninterrupted run; greedy
        outputs are bit-identical.  Sampled (temperature>0) requests
        continue but may diverge after the blip (the PRNG restarts)."""
        self.replays += 1
        engine.add_request(
            request_id=self.request_id,
            prompt=self.prompt,
            prompt_token_ids=(
                list(self.prompt_token_ids)
                if self.prompt_token_ids is not None
                else None
            ),
            sampling_params=self.sampling_params.clone(),
            trace_ctx=self.trace_ctx,
        )
        req = engine.scheduler.requests[self.request_id]
        if self.deadline_mono is not None:
            # The ORIGINAL deadline survives the replay: recovery must
            # not grant a request more wall-clock than an uninterrupted
            # run would have.
            req.deadline_mono = self.deadline_mono
        if not self.emitted_token_ids:
            return
        req.output_token_ids.extend(self.emitted_token_ids)
        req.resume_target = req.num_tokens
        # PREEMPTED makes admission resend prompt+outputs with the true
        # num_prompt_tokens boundary (scheduler.schedule's resumed path).
        req.status = RequestStatus.PREEMPTED
        if req.logprobs is not None and self.emitted_logprobs is not None:
            req.logprobs.extend(self.emitted_logprobs)
            req.cumulative_logprob = self.emitted_cumulative_logprob
        detok = engine.detokenizers.get(self.request_id)
        if detok is not None:
            # Pre-feed the delivered tokens so post-recovery text stays
            # cumulative and stop strings spanning the blip still match.
            detok.append(list(self.emitted_token_ids))

    # ---- drain-journal persistence (ISSUE 8) ----
    def to_dict(self) -> dict:
        """JSON-serializable form for the cross-process drain journal.
        deadline_mono is deliberately dropped (monotonic clocks don't
        transfer between processes; the resuming engine applies its own
        default)."""
        return {
            "request_id": self.request_id,
            "prompt": self.prompt,
            "prompt_token_ids": self.prompt_token_ids,
            "sampling_params": dataclasses.asdict(self.sampling_params),
            "emitted_token_ids": list(self.emitted_token_ids),
            "emitted_logprobs": (
                [
                    {str(k): v for k, v in lp.items()}
                    for lp in self.emitted_logprobs
                ]
                if self.emitted_logprobs is not None
                else None
            ),
            "emitted_cumulative_logprob": self.emitted_cumulative_logprob,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "JournalEntry":
        lps = d.get("emitted_logprobs")
        return cls(
            request_id=d["request_id"],
            prompt=d.get("prompt"),
            prompt_token_ids=d.get("prompt_token_ids"),
            sampling_params=SamplingParams(**d["sampling_params"]),
            emitted_token_ids=list(d.get("emitted_token_ids", ())),
            emitted_logprobs=(
                [{int(k): v for k, v in lp.items()} for lp in lps]
                if lps is not None
                else None
            ),
            emitted_cumulative_logprob=d.get(
                "emitted_cumulative_logprob", 0.0
            ),
        )


@dataclass
class RestartPolicy:
    """Bounded exponential-backoff restarts within a crash-loop window."""

    max_restarts: int
    backoff_base: float
    backoff_cap: float
    window: float

    @classmethod
    def from_env(cls) -> "RestartPolicy":
        return cls(
            max_restarts=envs.VDT_MAX_ENGINE_RESTARTS,
            backoff_base=envs.VDT_ENGINE_RESTART_BACKOFF_SECONDS,
            backoff_cap=envs.VDT_ENGINE_RESTART_BACKOFF_CAP_SECONDS,
            window=envs.VDT_CRASH_LOOP_WINDOW_SECONDS,
        )

    def backoff(self, attempt: int) -> float:
        return min(self.backoff_cap, self.backoff_base * 2**attempt)


class EngineSupervisor:
    """Owns the restart policy and runs the recovery cycle.  All state
    transitions happen on the AsyncLLM engine thread; the event loop
    only reads (``recovering``, ``last_failure``, ``retry_after``)."""

    def __init__(self, async_llm, policy: RestartPolicy | None = None):
        self.async_llm = async_llm
        self.policy = policy or RestartPolicy.from_env()
        self.recovering = False
        self.last_failure = None  # originating HostFailure of the cycle
        self.restarts_total = 0
        # vdt-lint: disable=unbounded-queue — pruned to the crash-loop
        # window on every use; length is bounded by max_restarts + 1
        self._restart_times: deque[float] = deque()
        # Guards _restart_times: can_recover is called from the event
        # loop (health checks, generate admission) while recover()
        # prunes/appends on the engine thread.
        self._times_lock = threading.Lock()
        self._current_backoff = self.policy.backoff_base
        self._interrupt = threading.Event()

    # ---- policy (also read from the event loop) ----
    def _prune(self, now: float) -> None:
        with self._times_lock:
            while (
                self._restart_times
                and now - self._restart_times[0] > self.policy.window
            ):
                self._restart_times.popleft()

    def _window_count(self) -> int:
        self._prune(time.monotonic())
        with self._times_lock:
            return len(self._restart_times)

    def _record_attempt(self, now: float) -> None:
        with self._times_lock:
            self._restart_times.append(now)

    def can_recover(self, failure) -> bool:
        """Would a death attributed to ``failure`` enter recovery (vs
        terminal)?  Only control-plane HostFailures are recoverable, and
        only while the crash-loop window has restart budget left."""
        if self.policy.max_restarts <= 0:
            return False
        if failure is None or not getattr(failure, "recoverable", False):
            return False
        return self._window_count() < self.policy.max_restarts

    def retry_after_seconds(self) -> int:
        """/health Retry-After while RECOVERING, derived from the
        backoff schedule (never below 1s)."""
        return max(1, math.ceil(self._current_backoff))

    def interrupt(self) -> None:
        """Abort backoff waits (AsyncLLM.shutdown during recovery)."""
        self._interrupt.set()

    # ---- the cycle (engine thread only) ----
    def recover(self, cause: BaseException) -> bool:
        """Attempt to bring the engine back.  Returns True with
        ``async_llm.engine`` swapped to a fresh engine and interrupted
        requests replayed, or False to fall through to terminal death."""
        llm = self.async_llm
        failure = getattr(llm.engine, "failure_info", None)
        if not self.can_recover(failure):
            return False
        self.last_failure = failure
        self.recovering = True
        llm._phase = "recovering"
        metrics = llm.engine.metrics
        # Flight-recorder artifact at the top of the cycle (ISSUE 12):
        # the dying engine's last steps, before teardown discards them.
        llm.engine.flight_recorder.dump(
            "recovery",
            extra=failure.to_dict() if failure is not None else None,
        )
        # Recovery transitions enter the unified timeline (ISSUE 20) on
        # the metrics object's log — it survives the engine swap.
        metrics.events.emit(
            "recovery_begin",
            cause=(
                failure.describe() if failure is not None else str(cause)
            ),
        )
        t0 = time.monotonic()
        try:
            # Settle the event loop first: outputs dispatched before the
            # death must land in the journal before we snapshot it.
            self._flush_event_loop()
            while True:
                if llm._shutdown or self._interrupt.is_set():
                    return False
                now = time.monotonic()
                attempt = self._window_count()
                if attempt >= self.policy.max_restarts:
                    logger.error(
                        "crash loop: %d engine restarts within %.0fs — "
                        "giving up, engine is permanently dead",
                        self.policy.max_restarts,
                        self.policy.window,
                    )
                    metrics.events.emit(
                        "recovery_failed",
                        reason="crash_loop",
                        restarts=self.policy.max_restarts,
                    )
                    return False
                self._record_attempt(now)
                self.restarts_total += 1
                metrics.record_restart()
                metrics.events.emit(
                    "recovery_attempt",
                    attempt=attempt + 1,
                    max_restarts=self.policy.max_restarts,
                )
                delay = self.policy.backoff(attempt)
                self._current_backoff = delay
                logger.warning(
                    "engine recovery: tearing down dead executor, "
                    "rebuild attempt %d/%d in %.1fs (%s)",
                    attempt + 1,
                    self.policy.max_restarts,
                    delay,
                    failure.describe() if failure is not None else cause,
                )
                self._teardown_old()
                if self._interrupt.wait(timeout=delay):
                    return False
                try:
                    from vllm_distributed_tpu.engine.llm_engine import (
                        LLMEngine,
                    )

                    new_engine = LLMEngine(llm.config, metrics=metrics)
                except Exception:  # noqa: BLE001 — retried per policy
                    logger.exception(
                        "engine rebuild attempt %d failed", attempt + 1
                    )
                    continue
                if llm._shutdown or self._interrupt.is_set():
                    # shutdown() raced the rebuild (its join gave up
                    # mid-constructor and nobody else will ever tear
                    # this engine down) — dismantle it here instead of
                    # leaking its listener/loop/pools into a dead
                    # process.
                    try:
                        new_engine.shutdown()
                    except Exception:  # noqa: BLE001
                        logger.exception(
                            "teardown of mid-shutdown rebuild raised"
                        )
                    return False
                llm.engine = new_engine
                # Admission reads scheduler state; point it at the
                # rebuilt scheduler before traffic resumes.
                llm._admission.attach_scheduler(new_engine.scheduler)
                replayed = self._replay(new_engine)
                metrics.record_engine_recovered()
                metrics.record_replayed(replayed)
                elapsed = time.monotonic() - t0
                metrics.record_recovery_seconds(elapsed)
                logger.warning(
                    "engine recovered in %.1fs (restart %d, %d request(s) "
                    "replayed)",
                    elapsed,
                    self.restarts_total,
                    replayed,
                )
                metrics.events.emit(
                    "recovery_success",
                    elapsed_s=round(elapsed, 3),
                    replayed=replayed,
                    restarts=self.restarts_total,
                )
                # The incident is closed: a LATER unrelated death must
                # not inherit this attribution via the failure_info
                # fallback.
                self.last_failure = None
                return True
        finally:
            self.recovering = False

    def _flush_event_loop(self) -> None:
        """Barrier: every callback the dead engine scheduled with
        call_soon_threadsafe (output dispatches -> journal updates) has
        run once this returns."""
        loop = self.async_llm._loop
        if loop is None:
            return
        settled = threading.Event()
        try:
            loop.call_soon_threadsafe(settled.set)
        except RuntimeError:
            return  # loop closed: nothing to settle
        settled.wait(timeout=2.0)

    def _teardown_old(self) -> None:
        try:
            self.async_llm.engine.shutdown()
        except Exception:  # noqa: BLE001 — a dead deployment tears down
            # as far as it can; the rebuild re-listens regardless.
            logger.exception("teardown of dead engine raised")

    def _replay(self, engine) -> int:
        """Re-admit journaled live requests on the rebuilt engine, in
        admission order.  Runs before the intake queue drains, so
        interrupted requests keep priority over work that arrived while
        recovering."""
        llm = self.async_llm
        replayed = 0
        now = time.monotonic()
        for entry in list(llm._journal.values()):
            if entry.finished or not entry.admitted:
                # finished: final output already delivered.  not
                # admitted: the "add" op still sits in the intake and
                # will reach this engine through the normal drain.
                continue
            if entry.deadline_mono is not None and now >= entry.deadline_mono:
                # Never replay an already-expired request (ISSUE 8):
                # re-prefilling work the deadline killed would spend
                # recovery time on output nobody waits for.  Deliver
                # the timeout finish the engine would have produced.
                entry.finished = True
                llm._to_request_queue(
                    entry.request_id, _timeout_output(entry, engine)
                )
                get_tracer().event(
                    entry.trace_ctx,
                    "engine.replay_expired",
                    request_id=entry.request_id,
                    emitted_tokens=len(entry.emitted_token_ids),
                )
                continue
            try:
                entry.replay_into(engine)
            except Exception as e:  # noqa: BLE001 — per-request error
                llm._to_request_queue(entry.request_id, e)
            else:
                replayed += 1
                get_tracer().event(
                    entry.trace_ctx,
                    "engine.replayed",
                    request_id=entry.request_id,
                    replays=entry.replays,
                    emitted_tokens=len(entry.emitted_token_ids),
                )
        return replayed
