"""Draftless speculative decoding: n-gram prompt-lookup proposer.

Decode is memory-bound (BENCH r03-r05: 0.5-1.5 GiB of KV traffic per
micro-step dominates step time), so verifying K drafted tokens in ONE
forward pass multiplies tokens-per-HBM-pass by the acceptance length —
the classic speculative-decoding win (Leviathan et al. 2023, PAPERS.md).
A separate draft model would need its own shards, compile cache, and
scheduler lane; the draftless *prompt-lookup* proposer (Saxena 2023; the
`[ngram]` speculative method in vLLM) drafts instead from the request's
OWN token history: if the tail n-gram of prompt+output has occurred
before, the tokens that followed that occurrence are proposed as drafts.
Free to produce, static-shape friendly, and precise exactly where the
memory-bound pain is worst — long repetitive stretches (code, JSON,
extraction, multi-turn chat with quoted context).

Verification is greedy-only and exact: the model runner runs the drafts
through one fused pass (`worker/model_runner._execute_spec_step`), the
accept kernel (`ops/sampling.spec_greedy_accept`) keeps the longest
prefix of drafts matching the argmax chain plus one bonus token, so
accepted tokens are precisely the tokens sequential greedy decode would
have produced — outputs are bit-identical to the non-speculative path
by construction, whatever the proposer guesses.

Scheduling contract (engine/scheduler.py): a spec step is an all-decode
step with ``SchedulerOutput.draft_token_ids`` carrying per-request
drafts and ``decode_steps == 1``; per-request ``num_scheduled_tokens``
is ``1 + len(drafts)`` and the ACTUAL advance (1 + accepted) is
reconciled in ``update_from_output`` from the emitted token count.
"""

from __future__ import annotations

from collections.abc import Sequence

from vllm_distributed_tpu.sampling_params import SamplingParams


def spec_eligible(sp: SamplingParams) -> bool:
    """True when a request can ride a speculative verify pass.

    Greedy-only by design: greedy accept/reject is exact (bit-identical
    outputs), while stochastic rejection sampling would need per-draft
    distribution bookkeeping.  Penalties are excluded because the
    penalized argmax depends on output history that changes *within*
    the pass; logprobs because the verify pass gathers [S, K+1] logits
    rows, not the per-step [S, V] fetches logprobs need.
    """
    return (
        sp.temperature == 0.0
        and sp.logprobs is None
        and sp.repetition_penalty == 1.0
        and sp.presence_penalty == 0.0
        and sp.frequency_penalty == 0.0
    )


class NgramProposer:
    """Per-request n-gram prompt-lookup draft proposer.

    ``propose`` matches the tail ``n``-gram of the token history
    (longest ``n`` first, ``max_n`` down to ``min_n``) against the
    EARLIEST prior occurrence in the history and returns up to
    ``max_draft`` tokens that followed it.  Earliest (not most recent)
    occurrence is deliberate: for periodic text the most recent match
    sits near the tail and truncates the continuation, while the
    earliest match has the whole cycle ahead of it — and in the
    chat/template workloads prompt-lookup targets, the earliest
    occurrence is the instruction/template copy being re-emitted.

    Pure host-side Python on the scheduler thread, anchored on the
    tail's FINAL token: candidate match positions come from C-speed
    ``list.index`` scans for that token, and only candidates are
    slice-compared against the pattern — so the common no-match case
    (non-repetitive text, large vocab) costs one C scan of the
    history, not a Python loop over it.  Wrong guesses cost only the
    wasted verify columns — never correctness.
    """

    # Candidate match positions examined per proposal: bounds the
    # pathological case (the tail's final token everywhere, the longer
    # pattern nowhere) to a constant amount of work per request per
    # step; past the cap the proposer just proposes nothing, which is
    # always safe.
    _MAX_CANDIDATES = 256

    def __init__(self, k: int, min_n: int = 1, max_n: int = 3) -> None:
        if k < 1:
            raise ValueError(f"spec ngram k must be >= 1, got {k}")
        if not 1 <= min_n <= max_n:
            raise ValueError(
                f"need 1 <= min_n <= max_n, got min_n={min_n} max_n={max_n}"
            )
        self.k = k
        self.min_n = min_n
        self.max_n = max_n

    def propose(
        self, tokens: Sequence[int], max_draft: int | None = None
    ) -> list[int]:
        """Draft up to ``min(self.k, max_draft)`` tokens continuing
        ``tokens`` (prompt + output history), or ``[]`` when no tail
        n-gram recurs."""
        budget = self.k if max_draft is None else min(self.k, max_draft)
        t = len(tokens)
        if budget <= 0 or t < self.min_n + 1:
            return []
        if not isinstance(tokens, list):
            tokens = list(tokens)
        last = tokens[-1]
        # Candidate match ends: every occurrence of the tail's final
        # token strictly before the final position, ascending (earliest
        # match wins), via C-speed index() scans.
        ends: list[int] = []
        j = 0
        while len(ends) < self._MAX_CANDIDATES:
            try:
                j = tokens.index(last, j, t - 1)
            except ValueError:
                break
            ends.append(j)
            j += 1
        if not ends:
            return []
        for n in range(min(self.max_n, t - 1), self.min_n - 1, -1):
            pattern = tokens[-n:]
            for end in ends:
                # A length-n match ends at `end` (may overlap the tail
                # itself — periodic text); `end` < t-1 guarantees at
                # least one draft token after it.
                i = end - n + 1
                if i >= 0 and tokens[i : end + 1] == pattern:
                    return tokens[end + 1 : end + 1 + budget]
        return []
