"""KV-page export/import for disaggregated prefill/decode (ISSUE 15).

DistServe (Zhong et al., OSDI 2024) and Splitwise (Patel et al., ISCA
2024) separate the compute-bound prefill phase from the memory-bound
decode phase onto different pools and hand the KV cache across at the
phase boundary.  This module is the replica-side half of that hand-off:

- **Export** (prefill replica): a request admitted with
  ``SamplingParams.prefill_only`` runs prefill plus its first sampled
  token and then finishes with its KV pages **held** instead of freed
  (the scheduler routes the release here).  The router then pulls the
  pages in per-layer chunks — each chunk is one ``export_kv_pages``
  worker RPC reusing the PR 14 ``jax.device_get`` gather — and finally
  releases the hold.  Holds carry a TTL so a router that dies
  mid-hand-off can never leak pool pages.

- **Import** (decode replica): ``begin_import`` reserves fresh pages
  out of every index (``allocator.take_pages`` — invisible to eviction
  and reuse until commit), ``apply_chunk`` scatters each received layer
  chunk into them via the ``import_kv_pages`` worker RPC (the PR 14
  donated in-place scatter), and ``commit`` registers the now-complete
  pages as a cached radix chain over the prompt tokens
  (``allocator.adopt_chain``).  The subsequent ``/internal/resume``
  admission then finds the chain through the ordinary PR 14
  ``plan_prefix``/``attach_plan`` path and counts the transferred
  tokens as computed — decode continues bit-identically, with only the
  tail page recomputed (the same at-least-one-token contract every
  prefix-cache hit obeys).

Every byte on the wire is checksummed per layer chunk (sha256, verified
worker-side before any scatter): a corrupt or mis-ordered transfer
aborts the import and the router falls back to the PR 8
recompute-resume — never garbage KV.

All methods run on the engine thread (AsyncLLM routes them over the aux
path), so allocator mutation is serialized with the scheduler and the
worker RPCs stay ordered with step dispatches on a multihost mesh.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.engine.request import Request, RequestStatus
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

# Deadline on each export/import worker RPC: a wedged device must fail
# the hand-off (router falls back to recompute) rather than park the
# engine thread.
_RPC_TIMEOUT_SECONDS = 60.0


class KVTransferError(RuntimeError):
    """Typed hand-off failure (unknown handle, incomplete transfer,
    checksum mismatch surfaced by the worker, unsupported allocator).
    The API layer maps it to a 4xx/5xx the router treats as 'abort the
    transfer and fall back to recompute-resume'."""


@dataclass
class _Hold:
    """One finished prefill-only request whose pages await export."""

    req: Request
    pages: list[int]
    token_ids: list[int]  # full-page prompt prefix the pages cover
    created_mono: float
    deadline_mono: float


@dataclass
class _Import:
    """One in-progress inbound transfer (begin .. commit/abort)."""

    transfer_id: str
    pages: list[int]
    token_ids: list[int]
    created_mono: float
    deadline_mono: float
    num_layers: int | None = None  # learned from the first chunk
    received: set[int] = field(default_factory=set)
    bytes_in: int = 0


class KVTransferManager:
    """Owns export holds and import transfers for one engine.  Engine
    thread only; the ``active`` flag keeps the scheduler's sweep hook at
    one attribute read per step while disaggregation is idle."""

    def __init__(self, scheduler, executor, metrics, tracer=None) -> None:
        self.scheduler = scheduler
        self.executor = executor
        self.metrics = metrics
        self.tracer = tracer
        self.ttl = envs.VDT_DISAGG_EXPORT_TTL_SECONDS
        self.holds: dict[str, _Hold] = {}
        self.imports: dict[str, _Import] = {}
        self._seq = 0

    # ---- scheduler-facing (finish-time hook + TTL sweep) ----
    @property
    def active(self) -> bool:
        return bool(self.holds or self.imports)

    def wants_hold(self, req: Request) -> bool:
        """True when this finishing request's pages should be held for
        export instead of freed: a prefill-only request that ran to its
        one-token budget (an abort means the router is gone, a stop/EOS
        means the request REALLY finished and there is nothing to hand
        off) and owns at least one full prompt page."""
        if not req.sampling_params.prefill_only:
            return False
        if req.status is not RequestStatus.FINISHED_LENGTH:
            return False
        ps = self.scheduler.page_size
        return req.num_prompt_tokens >= ps and bool(req.page_ids)

    def hold(self, req: Request) -> None:
        """Adopt a finishing prefill-only request's pages (called by the
        scheduler INSTEAD of freeing them).  Only the full pages covering
        the prompt are exportable — the partial tail page (and the first
        sampled token's row) is recomputed decode-side, the same
        page-boundary contract every prefix-cache hit obeys."""
        ps = self.scheduler.page_size
        full = req.num_prompt_tokens // ps
        now = time.monotonic()
        self.holds[req.request_id] = _Hold(
            req=req,
            pages=list(req.page_ids[:full]),
            token_ids=list(req.prompt_token_ids[: full * ps]),
            created_mono=now,
            deadline_mono=now + self.ttl,
        )

    def sweep(self, now_mono: float) -> None:
        """Free expired holds and abort expired imports (TTL guard: a
        dead router must never leak pool pages)."""
        for rid in [
            r for r, h in self.holds.items() if now_mono >= h.deadline_mono
        ]:
            logger.warning(
                "kv export hold %s expired after %.0fs; freeing pages",
                rid,
                self.ttl,
            )
            self.release(rid)
        for tid in [
            t
            for t, imp in self.imports.items()
            if now_mono >= imp.deadline_mono
        ]:
            logger.warning(
                "kv import %s expired after %.0fs; returning pages",
                tid,
                self.ttl,
            )
            self.abort_import(tid)

    # ---- export (prefill replica) ----
    def export(
        self, handle: str, layer_start: int, layer_count: int
    ) -> dict:
        """One per-layer chunk of the held pages' KV, gathered from the
        reply-rank worker, plus the chain metadata the decode side needs
        (token ids, page count, total layer count).  Chunks are pure
        reads — the hold stays live until ``release``."""
        hold = self.holds.get(handle)
        if hold is None:
            raise KVTransferError(f"unknown export handle {handle!r}")
        out = self.executor.collective_rpc(
            "export_kv_pages",
            (hold.pages, int(layer_start), int(layer_count)),
            unique_reply_rank=self.executor.output_rank,
            timeout=_RPC_TIMEOUT_SECONDS,
        )
        if not isinstance(out, dict):
            raise KVTransferError("worker export returned no payload")
        layers = out.get("layers") or []
        nbytes = sum(len(layer.get("data") or b"") for layer in layers)
        if self.metrics is not None:
            self.metrics.record_kv_transfer(
                "out", pages=len(hold.pages) * len(layers), nbytes=nbytes
            )
        return {
            "num_layers": int(out.get("num_layers", 0)),
            "layers": layers,
            "num_pages": len(hold.pages),
            "token_ids": list(hold.token_ids),
            "page_size": self.scheduler.page_size,
        }

    def release(self, handle: str) -> bool:
        """Free a hold's pages (export finished, failed, or expired).
        Idempotent; records the export wall on a real release."""
        hold = self.holds.pop(handle, None)
        if hold is None:
            return False
        if self.metrics is not None:
            self.metrics.record_kv_transfer_seconds(
                time.monotonic() - hold.created_mono
            )
        self.scheduler.release_hold_pages(hold.req)
        return True

    # ---- import (decode replica) ----
    def _allocator(self):
        allocator = self.scheduler.allocator
        if not getattr(allocator, "supports_tiered", False):
            raise KVTransferError(
                "KV import needs the radix prefix index "
                "(--enable-prefix-caching with --prefix-cache-index radix)"
            )
        return allocator

    def begin_import(
        self, token_ids: list[int], resume_from: str | None = None
    ) -> dict:
        """Reserve pages for an inbound chain.  Returns transfer_id=None
        when there is nothing importable (sub-page prompt) or the pool
        cannot spare the pages — the router then skips the transfer and
        resumes with recompute, which is always correct.

        With ``resume_from`` (ISSUE 19) the router lost a chunk
        round-trip and asks which layers actually landed: if the named
        import is still live and covers the same prompt prefix, the
        existing reservation is returned along with its ``received``
        layer indices so the router re-pulls only the missing ones.
        Anything else (TTL expiry, scatter-failure abort, token
        mismatch) returns transfer_id=None and the router falls back."""
        allocator = self._allocator()
        ps = self.scheduler.page_size
        full = len(token_ids) // ps
        if resume_from is not None:
            imp = self.imports.get(resume_from)
            if imp is None or imp.token_ids != list(
                token_ids[: len(imp.token_ids)]
            ):
                return {"transfer_id": None, "num_pages": 0}
            imp.deadline_mono = time.monotonic() + self.ttl
            return {
                "transfer_id": imp.transfer_id,
                "num_pages": len(imp.pages),
                "received": sorted(imp.received),
                "num_layers": imp.num_layers,
            }
        if full <= 0:
            return {"transfer_id": None, "num_pages": 0}
        from vllm_distributed_tpu.engine.block_manager import (
            NoFreePagesError,
        )

        try:
            pages = allocator.take_pages(full)
        except NoFreePagesError:
            return {"transfer_id": None, "num_pages": 0}
        self._seq += 1
        tid = f"kvimp-{self._seq}"
        now = time.monotonic()
        self.imports[tid] = _Import(
            transfer_id=tid,
            pages=pages,
            token_ids=list(token_ids[: full * ps]),
            created_mono=now,
            deadline_mono=now + self.ttl,
        )
        return {"transfer_id": tid, "num_pages": full}

    def apply_chunk(self, transfer_id: str, layers: list[dict]) -> dict:
        """Scatter one received layer chunk into the reserved pages.
        The worker verifies each layer's checksum BEFORE writing; a
        mismatch raises and the caller aborts the transfer."""
        imp = self.imports.get(transfer_id)
        if imp is None:
            raise KVTransferError(
                f"unknown import transfer {transfer_id!r}"
            )
        if not layers:
            return {"received_layers": len(imp.received)}
        try:
            out = self.executor.collective_rpc(
                "import_kv_pages",
                (imp.pages, layers),
                unique_reply_rank=self.executor.output_rank,
                timeout=_RPC_TIMEOUT_SECONDS,
            )
        except Exception:
            # A failed scatter leaves page content indeterminate: the
            # transfer is unusable, free the reservation immediately.
            self.abort_import(transfer_id)
            raise
        if out is not None and not out.get("ok", True):
            self.abort_import(transfer_id)
            raise KVTransferError(
                str(out.get("error") or "worker rejected kv chunk")
            )
        for layer in layers:
            imp.received.add(int(layer["index"]))
            imp.bytes_in += len(layer.get("data") or b"")
            nl = layer.get("num_layers")
            if nl is not None:
                imp.num_layers = int(nl)
        return {"received_layers": len(imp.received)}

    def commit_import(self, transfer_id: str) -> dict:
        """Register a COMPLETE transfer's pages as a cached radix chain
        (the decode-side admission finds them via plan_prefix).  An
        incomplete transfer (missing layers) aborts instead — serving a
        half-scattered page as a prefix hit would be garbage KV."""
        imp = self.imports.get(transfer_id)
        if imp is None:
            raise KVTransferError(
                f"unknown import transfer {transfer_id!r}"
            )
        if imp.num_layers is None or len(imp.received) < imp.num_layers:
            got = sorted(imp.received)
            self.abort_import(transfer_id)
            raise KVTransferError(
                f"incomplete kv transfer: received layers {got} of "
                f"{imp.num_layers}"
            )
        del self.imports[transfer_id]
        allocator = self._allocator()
        adopted, _ = allocator.adopt_chain(imp.token_ids, imp.pages)
        ps = self.scheduler.page_size
        dur = time.monotonic() - imp.created_mono
        if self.metrics is not None:
            self.metrics.record_kv_transfer(
                "in",
                pages=len(imp.pages) * imp.num_layers,
                nbytes=imp.bytes_in,
            )
            self.metrics.record_kv_transfer_seconds(dur)
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.record_span(
                "engine.kv_handoff",
                time.time() - dur,
                dur,
                transfer_id=transfer_id,
                pages=len(imp.pages),
                adopted_pages=adopted,
                bytes=imp.bytes_in,
            )
        if self.metrics is not None:
            self.metrics.events.emit(
                "kv_handoff",
                outcome="adopted",
                transfer_id=transfer_id,
                pages=len(imp.pages),
                adopted_pages=adopted,
                duration_s=round(dur, 6),
            )
        return {
            "adopted_pages": adopted,
            "adopted_tokens": adopted * ps,
        }

    def abort_import(self, transfer_id: str) -> bool:
        """Return an unfinished transfer's reserved pages to the free
        list.  Idempotent.  Safe even after partial scatters: the pages
        were never indexed, so nothing can ever read them as a hit."""
        imp = self.imports.pop(transfer_id, None)
        if imp is None:
            return False
        self._allocator().return_pages(imp.pages)
        if self.metrics is not None:
            self.metrics.events.emit(
                "kv_handoff",
                outcome="aborted",
                transfer_id=transfer_id,
                pages=len(imp.pages),
            )
        return True
