"""Continuous-batching scheduler.

The engine-side capability the reference delegates to vLLM v1's scheduler
(SURVEY.md §2.3; the scheduler's product, `SchedulerOutput`, is exactly
what CustomExecutor.execute_model receives at launch.py:322).  Design:

- Single token budget per step (`max_num_batched_tokens`), shared by
  prefill and decode; chunked prefill lets long prompts trickle through
  without starving decodes (TPU-friendly: step shapes stay bounded, so the
  number of distinct compiled programs stays small).
- Workers mirror request state, so `SchedulerOutput` carries full data only
  for newly-scheduled requests and deltas for cached ones — matching the
  reference's control-plane economy (only small control messages cross
  hosts per step, SURVEY.md §2.5).
- Preemption by eviction: when KV pages run out, the lowest-priority
  running request is stopped, its pages freed, and it re-enters the
  waiting queue for full recompute (same policy family as vLLM's
  recompute preemption).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from vllm_distributed_tpu.config import CacheConfig, SchedulerConfig
from vllm_distributed_tpu.engine.block_manager import (
    NoFreePagesError,
    PageAllocator,
    PrefixCachingAllocator,
    RadixPrefixCachingAllocator,
)
from vllm_distributed_tpu.engine.qos import QosRegistry
from vllm_distributed_tpu.engine.request import Request, RequestStatus
from vllm_distributed_tpu.engine.spec_decode import spec_eligible
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.tracing import get_tracer

logger = init_logger(__name__)

# Spec-decode pipelining hysteresis (ISSUE 11): after this many
# consecutive draftless spec-eligible schedules the engine resumes
# async dispatch pipelining (spec dormant)...
_SPEC_DRY_LIMIT = 4
# ...and drains it for one probing schedule every this many pipelined
# schedules, so a workload that turns repetitive re-engages spec.
_SPEC_PROBE_INTERVAL = 16


@dataclass
class NewRequestData:
    req_id: str
    # On preemption-resume this includes previously generated tokens (the
    # worker re-prefills them); num_prompt_tokens marks the true
    # prompt/output boundary so penalties stay correct across preemption.
    prompt_token_ids: list[int]
    num_prompt_tokens: int
    page_ids: list[int]
    num_computed_tokens: int
    num_new_tokens: int
    sampling_params: SamplingParams


@dataclass
class CachedRequestData:
    req_id: str
    new_page_ids: list[int]
    num_computed_tokens: int
    num_new_tokens: int


@dataclass
class SchedulerOutput:
    """One step's worth of work, shipped to every worker."""

    step_id: int
    new_requests: list[NewRequestData] = field(default_factory=list)
    cached_requests: list[CachedRequestData] = field(default_factory=list)
    # req_id -> num tokens to run this step (prefill chunk len, or the
    # decode_steps fused this dispatch).
    num_scheduled_tokens: dict[str, int] = field(default_factory=dict)
    total_num_scheduled_tokens: int = 0
    finished_req_ids: list[str] = field(default_factory=list)
    preempted_req_ids: list[str] = field(default_factory=list)
    # >1 = every scheduled request is a decode and the worker runs this
    # many fused decode micro-steps on device (one sampled token each).
    decode_steps: int = 1
    # Speculative decoding (ISSUE 11): req_id -> drafted tokens to
    # verify this step.  Non-empty marks a spec verify step: every
    # scheduled request is a decode, its num_scheduled_tokens is
    # 1 + len(drafts) (the step's input token + the drafts), the worker
    # verifies all drafts in ONE fused pass, and the ACTUAL per-request
    # advance (1 + accepted drafts) is reconciled in update_from_output
    # from the emitted token count.  decode_steps is always 1 here.
    draft_token_ids: dict[str, list[int]] = field(default_factory=dict)
    # Tiered KV cache (ISSUE 14): (hbm_page, host_slot) spans whose KV
    # the workers copy out to host DRAM, and (host_slot, hbm_page)
    # spans they stream back, BEFORE executing this step — spills must
    # land before the evicted page is overwritten, restores before the
    # restored pages are read.  Applied in order: all spills, then all
    # restores.
    kv_spill_ops: list[tuple[int, int]] = field(default_factory=list)
    kv_restore_ops: list[tuple[int, int]] = field(default_factory=list)
    # Trace context of the first scheduled traced request, if any: the
    # parent for this step's schedule/dispatch/gather spans (a step
    # serves a batch, so one trace adopts the step; the others link via
    # the schedule span's batch attributes).
    trace_ctx: tuple | None = None

    @property
    def is_empty(self) -> bool:
        return self.total_num_scheduled_tokens == 0


class Scheduler:
    def __init__(
        self,
        scheduler_config: SchedulerConfig,
        cache_config: CacheConfig,
        num_pages: int,
    ) -> None:
        self.config = scheduler_config
        self.page_size = cache_config.page_size
        # Prefix caching swaps the allocator behind the same interface;
        # with the flag off the seed allocator (and behaviour) is
        # untouched.  The radix index (ISSUE 14) is the default cache;
        # "flat" keeps the PR 1 hash-chain as the ablation baseline.
        self.enable_prefix_caching = cache_config.enable_prefix_caching
        if not self.enable_prefix_caching:
            self.allocator = PageAllocator(num_pages, cache_config.page_size)
        elif cache_config.prefix_cache_index == "flat":
            self.allocator = PrefixCachingAllocator(
                num_pages, cache_config.page_size
            )
        else:
            self.allocator = RadixPrefixCachingAllocator(
                num_pages,
                cache_config.page_size,
                host_pages=cache_config.kv_spill_host_pages,
                restore_min_tokens=cache_config.kv_spill_restore_min_tokens,
            )
        self._tiered = isinstance(
            self.allocator, RadixPrefixCachingAllocator
        )
        # Bounded upstream by the AdmissionController caps when
        # configured (engine/overload.py); unbounded growth is the
        # operator's explicit choice via max_waiting_requests=0.
        # vdt-lint: disable=unbounded-queue — bound enforced at admission
        self.waiting: deque[Request] = deque()
        # Prompt tokens awaiting (re-)prefill across self.waiting — an
        # integer mirror maintained at every waiting mutation so the
        # event-loop admission check reads one int instead of iterating
        # a deque the engine thread mutates (ISSUE 8).
        self.num_waiting_tokens = 0
        self.running: list[Request] = []
        self.requests: dict[str, Request] = {}
        self._step_id = 0
        # Finished/preempted since last step, to notify workers.
        self._finished_since_last: list[str] = []
        # Notices that rode an EMPTY SchedulerOutput: the engine never
        # dispatches empty steps, so without holding them here the
        # workers would silently keep mirrored state for finished/
        # preempted requests forever (and the step-delta encoder would
        # desynchronize from the worker mirrors).
        self._held_notices: tuple[list[str], list[str]] | None = None
        # Requests that finished/aborted while LATER steps containing
        # them were still in flight on the device: their KV pages are
        # freed only once every in-flight step has drained, so the
        # device can never be writing into pages the allocator has
        # already handed to another request (async-scheduling
        # reconciliation, ISSUE 7).
        self._deferred_frees: dict[str, Request] = {}
        # Cumulative preemption count (metrics, SURVEY.md §5.5).
        self.num_preemptions = 0
        # Cumulative prefix-cache token counters (metrics): tokens
        # eligible for lookup at admission vs tokens served from cache.
        # `prefix_cache_hits` is the TOTAL across tiers;
        # `prefix_cache_hits_host` is the host-restored share of it.
        self.prefix_cache_queries = 0
        self.prefix_cache_hits = 0
        self.prefix_cache_hits_host = 0
        # Cumulative tier-traffic counters (ISSUE 14 metrics).
        self.kv_spill_pages = 0
        self.kv_restore_pages = 0
        # Tier-op spans produced by a schedule whose output came up
        # EMPTY (e.g. the triggering admission rolled back): held for
        # the next step that actually reaches the workers, exactly like
        # _held_notices — a spill must still beat any later reuse of
        # its source page.
        self._held_tier_ops: tuple[list, list] | None = None
        # Requests finished OUTSIDE update_from_output (deadline sheds,
        # preempt-to-shed): the engine drains this after each schedule
        # and emits their final RequestOutputs (ISSUE 8).
        self._finished_out_of_band: list[Request] = []
        # Cumulative overload counters (metrics).
        self.num_timeouts = 0
        self.num_sheds = 0
        # True while any live request carries a deadline (sticky; reset
        # when the scheduler empties) so deadline enforcement costs one
        # attribute read per step when unused.
        self._has_deadlines = False
        # Speculative decoding (ISSUE 11): the n-gram prompt-lookup
        # proposer, built only when --speculative-ngram-k > 0 so the
        # default path pays one attribute read per step.
        self.spec = None
        if scheduler_config.spec_ngram_k > 0:
            from vllm_distributed_tpu.engine.spec_decode import (
                NgramProposer,
            )

            self.spec = NgramProposer(
                scheduler_config.spec_ngram_k,
                min_n=scheduler_config.spec_ngram_min,
                max_n=scheduler_config.spec_ngram_max,
            )
        # Cumulative spec-decode token counters (metrics): tokens
        # drafted into verify passes vs drafts accepted by them.
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        # Pipelining hysteresis state (see spec_wants_sync).
        self._spec_dry_streak = 0
        self._spec_pipeline_steps = 0
        # Disaggregated prefill (ISSUE 15): installed by LLMEngine when
        # the engine builds its KVTransferManager.  None = the finish
        # path below never holds pages (seed behavior); while idle the
        # manager costs one attribute read per schedule.
        self.kv_transfer = None
        # QoS control plane (ISSUE 16): class registry driving priority
        # admission order, class-weighted preemption, and the per-class
        # waiting mirrors the admission shares read.  Disabled (the
        # default) keeps every decision on the seed code path.
        self.qos = QosRegistry.parse(scheduler_config.qos_classes)
        # Per-class mirrors of len(waiting)/num_waiting_tokens, keyed by
        # the RESOLVED class bucket (unknown names fold into "default",
        # so the keyspace is capped by the registry).  Maintained only
        # while QoS is enabled.
        self.waiting_by_class: dict[str, int] = {}
        self.waiting_tokens_by_class: dict[str, int] = {}
        # Cumulative per-class preempt/shed counters (QoS only): the
        # acceptance evidence that evictions land on the lowest class.
        self.preemptions_by_class: dict[str, int] = {}
        self.sheds_by_class: dict[str, int] = {}

    # ---- QoS lookups (ISSUE 16) ----
    def _qos_priority(self, req: Request) -> int:
        return self.qos.resolve(req.sampling_params.slo_class).priority

    def _qos_bucket(self, req: Request) -> str:
        return self.qos.resolve(req.sampling_params.slo_class).name

    # ---- waiting-queue mutation (ALL of it goes through these three
    # helpers so num_waiting_tokens can never drift from the deque) ----
    def _waiting_push(self, req: Request, left: bool = False) -> None:
        if left:
            self.waiting.appendleft(req)
        else:
            self.waiting.append(req)
        tokens = req.prefill_target - req.num_computed_tokens
        self.num_waiting_tokens += tokens
        if self.qos.enabled:
            cls = self._qos_bucket(req)
            self.waiting_by_class[cls] = (
                self.waiting_by_class.get(cls, 0) + 1
            )
            self.waiting_tokens_by_class[cls] = (
                self.waiting_tokens_by_class.get(cls, 0) + tokens
            )

    def _waiting_pop(self, req: Request, popleft: bool = False) -> None:
        if popleft:
            self.waiting.popleft()
        else:
            self.waiting.remove(req)
        tokens = req.prefill_target - req.num_computed_tokens
        self.num_waiting_tokens = max(
            self.num_waiting_tokens - tokens, 0
        )
        if self.qos.enabled:
            cls = self._qos_bucket(req)
            self.waiting_by_class[cls] = max(
                self.waiting_by_class.get(cls, 0) - 1, 0
            )
            self.waiting_tokens_by_class[cls] = max(
                self.waiting_tokens_by_class.get(cls, 0) - tokens, 0
            )

    # ---- intake ----
    def add_request(self, req: Request) -> None:
        # A request that can never fit in the page pool would wait forever;
        # reject it up front. +1 covers the first sampled token's slot.
        usable_pages = self.allocator.num_pages - 1
        max_len = min(req.max_total_tokens, self.config.max_model_len)
        if self.allocator.num_pages_needed(max_len) > usable_pages:
            raise ValueError(
                f"request {req.request_id} needs up to {max_len} KV slots "
                f"({self.allocator.num_pages_needed(max_len)} pages) but the "
                f"cache holds only {usable_pages} pages of "
                f"{self.page_size} slots"
            )
        if req.num_prompt_tokens >= self.config.max_model_len:
            raise ValueError(
                f"prompt of request {req.request_id} has "
                f"{req.num_prompt_tokens} tokens, exceeding max_model_len "
                f"{self.config.max_model_len}"
            )
        if req.num_prompt_tokens == 0:
            raise ValueError(f"request {req.request_id} has an empty prompt")
        if (
            not self.config.enable_chunked_prefill
            and req.num_prompt_tokens > self.config.max_num_batched_tokens
        ):
            raise ValueError(
                f"prompt of request {req.request_id} has "
                f"{req.num_prompt_tokens} tokens but chunked prefill is "
                f"disabled and the step budget is "
                f"{self.config.max_num_batched_tokens}"
            )
        self.requests[req.request_id] = req
        if req.deadline_mono is not None:
            self._has_deadlines = True
        self._waiting_push(req)

    def abort_request(self, req_id: str) -> None:
        req = self.requests.get(req_id)
        if req is None or req.status.is_finished:
            return
        req.status = RequestStatus.FINISHED_ABORTED
        if req in self.running:
            self.running.remove(req)
            self._finished_since_last.append(req_id)
        elif req in self.waiting:
            self._waiting_pop(req)
        self._release_or_defer(req)
        del self.requests[req_id]

    def _release_or_defer(self, req: Request) -> None:
        """Free a finished request's pages — unless a later in-flight
        step still references them (pipelined scheduling ran ahead of
        this finish), in which case the free waits for those steps to
        drain (``update_from_output`` settles the debt).  A finishing
        prefill-only request (ISSUE 15) instead HOLDS its pages for the
        router's KV-page export; the hold's release (or TTL expiry)
        comes back through ``release_hold_pages``."""
        if (
            self.kv_transfer is not None
            and req.num_inflight_tokens == 0
            and self.kv_transfer.wants_hold(req)
        ):
            self.kv_transfer.hold(req)
            return
        if req.num_inflight_tokens > 0:
            self._deferred_frees[req.request_id] = req
        else:
            self.allocator.free(req)

    def release_hold_pages(self, req: Request) -> None:
        """Free a KV-export hold's pages (engine/kv_transfer.py calls
        this on release/expiry — the deferred path mirrors
        _release_or_defer for safety, though a held prefill never has
        steps in flight)."""
        if req.num_inflight_tokens > 0:
            self._deferred_frees[req.request_id] = req
        else:
            self.allocator.free(req)

    @property
    def num_unfinished(self) -> int:
        return len(self.waiting) + len(self.running)

    @property
    def kv_cache_usage(self) -> float:
        """Fraction of usable KV pages held by live requests (cached
        pages awaiting reuse count as free — they are evictable)."""
        usable = self.allocator.num_pages - 1  # page 0 reserved
        return 1.0 - self.allocator.num_free_pages / max(usable, 1)

    def has_unfinished_requests(self) -> bool:
        return self.num_unfinished > 0

    # ---- deadlines + load shedding (ISSUE 8) ----
    def _shed_expired(self, now_mono: float) -> None:
        """Enforce per-request deadlines at schedule time (the cheap
        place: one monotonic read, two short scans).  Expired WAITING
        requests are shed before any prefill is spent on them — the
        workers never knew them (or already dropped them at preemption),
        so no notice is emitted.  Expired RUNNING requests finish with
        finish_reason="timeout" and whatever partial output they have;
        their finish notice rides this step's output like any other
        finish."""
        for req in [r for r in self.waiting if r.expired(now_mono)]:
            self._waiting_pop(req)
            req.status = RequestStatus.FINISHED_TIMEOUT
            self._release_or_defer(req)
            del self.requests[req.request_id]
            self._finished_out_of_band.append(req)
            self.num_timeouts += 1
            get_tracer().event(
                req.trace_ctx,
                "engine.deadline_shed",
                request_id=req.request_id,
                stage="waiting",
            )
        for req in [r for r in self.running if r.expired(now_mono)]:
            self.running.remove(req)
            req.status = RequestStatus.FINISHED_TIMEOUT
            self._finished_since_last.append(req.request_id)
            self._release_or_defer(req)
            del self.requests[req.request_id]
            self._finished_out_of_band.append(req)
            self.num_timeouts += 1
            get_tracer().event(
                req.trace_ctx,
                "engine.deadline_shed",
                request_id=req.request_id,
                stage="running",
                num_output_tokens=req.num_output_tokens,
            )

    def take_finished_out_of_band(self) -> list[Request]:
        """Drain requests finished outside update_from_output (deadline
        sheds, preempt-to-shed) so the engine can emit their final
        outputs."""
        if not self._finished_out_of_band:
            return []
        out, self._finished_out_of_band = self._finished_out_of_band, []
        return out

    # ---- the step ----
    def schedule(self) -> SchedulerOutput:
        # Sticky flag, not a per-step scan: with no deadlines anywhere
        # (the default) this is one attribute read per step.
        if self._has_deadlines:
            if self.requests:
                self._shed_expired(time.monotonic())
            else:
                self._has_deadlines = False
        if self.kv_transfer is not None and self.kv_transfer.active:
            # Disagg TTL guard (ISSUE 15): expired export holds and
            # orphaned imports free their pages here — a router that
            # died mid-hand-off can never leak pool capacity.
            self.kv_transfer.sweep(time.monotonic())
        out = SchedulerOutput(step_id=self._step_id)
        self._step_id += 1
        out.finished_req_ids = self._finished_since_last
        self._finished_since_last = []

        token_budget = self.config.max_num_batched_tokens

        # Chunked-prefill fairness budget (ISSUE 16): while any
        # decode-bound request of higher-or-equal class is running,
        # prefill chunks collectively take at most qos_prefill_share of
        # the step budget, so a 32k-token prefill can no longer starve
        # decode ITL on a mixed replica.  Work-conserving: with no
        # qualifying decode running, prefill fills whatever budget is
        # left — exactly the seed policy.  Off (share=0, the default)
        # this whole block is two config reads.
        prefill_cap: int | None = None
        max_decode_prio = 0
        if (
            self.config.enable_chunked_prefill
            and 0.0 < self.config.qos_prefill_share < 1.0
        ):
            decode_prios = [
                self._qos_priority(r) if self.qos.enabled else 0
                for r in self.running
                if not r.is_prefill
            ]
            if decode_prios:
                max_decode_prio = max(decode_prios)
                prefill_cap = max(
                    int(
                        self.config.qos_prefill_share
                        * self.config.max_num_batched_tokens
                    ),
                    1,
                )
        prefill_used = 0

        # Multi-step decode: when the whole batch is decoding and nothing
        # is waiting to be admitted, fuse K decode steps into one device
        # dispatch.  K is UNIFORM (the configured value, clamped only by
        # the shared token budget): a request whose remaining length
        # budget is under K is scheduled with num_new < K and the worker
        # masks its trailing micro-steps on device.  This keeps ONE
        # compiled scan length per config — the r4 design derived K from
        # min(remaining room), so every request tail walked K down
        # through 8/4/2/1 and compiled a fresh multi-second program
        # mid-serve (measured 14-23 s each on v5e).  Logprobs force K=1
        # (per-step [S, V] logprob fetches don't amortize).
        #
        # Speculative decoding (ISSUE 11) takes precedence on the same
        # all-decode precondition: when the n-gram proposer drafts for
        # at least one request, the step becomes a single-dispatch
        # verify pass (decode_steps=1, per-request num_new = 1+drafts)
        # instead of a K-step scan — one HBM pass for up to K+1 tokens
        # rather than one per token.  Steps where nothing drafts fall
        # back to the fused scan, so non-repetitive stretches keep the
        # fused-decode throughput.
        decode_only = bool(
            self.running
            and not self.waiting
            and all(not r.is_prefill for r in self.running)
            and all(
                r.sampling_params.logprobs is None for r in self.running
            )
        )
        spec_drafts = self._propose_drafts() if decode_only else {}
        k = 1
        if (
            not spec_drafts
            and decode_only
            and self.config.num_decode_steps > 1
        ):
            k = self.config.fused_decode_steps()
        out.decode_steps = k

        # 1) decodes + in-flight chunked prefills, in arrival order.
        #    Iterate over a copy: preemption mutates self.running.
        scheduled_running: list[Request] = []
        preempted: set[str] = set()
        for req in list(self.running):
            if req.request_id in preempted:
                continue
            if token_budget <= 0:
                break
            drafts = None
            if req.is_prefill:
                remaining = req.prefill_target - req.num_computed_tokens
                chunk = min(remaining, token_budget)
                if (
                    prefill_cap is not None
                    and self._qos_priority(req) <= max_decode_prio
                ):
                    chunk = min(chunk, prefill_cap - prefill_used)
                    if chunk <= 0:
                        continue
                if not self.config.enable_chunked_prefill and chunk < remaining:
                    continue
                num_new = chunk
            else:
                # Skip decodes that already have their whole remaining
                # budget in flight (pipelining: results not applied yet).
                room = (
                    min(req.max_total_tokens, self.config.max_model_len)
                    - req.num_tokens
                    - req.num_inflight_tokens
                )
                if room <= 0:
                    continue
                drafts = spec_drafts.get(req.request_id)
                if drafts is not None and token_budget <= len(drafts):
                    # The shared budget cuts this verify window short;
                    # trim drafts rather than overrun the step budget.
                    drafts = drafts[: token_budget - 1] or None
                if drafts is not None:
                    # Spec verify window: the input token + the drafts
                    # (already room-capped at proposal time).
                    num_new = 1 + len(drafts)
                else:
                    # Under-K tails are masked on device, not given
                    # their own scan length (see the K comment above).
                    num_new = min(k, room)
            got = self._allocate_or_preempt(
                req,
                req.num_inflight_tokens + num_new,
                preempted,
                scheduled_running,
            )
            if not got:
                continue
            new_pages = got[1]
            out.num_scheduled_tokens[req.request_id] = num_new
            out.total_num_scheduled_tokens += num_new
            token_budget -= num_new
            if out.trace_ctx is None:
                out.trace_ctx = req.trace_ctx
            out.cached_requests.append(
                CachedRequestData(
                    req_id=req.request_id,
                    new_page_ids=new_pages,
                    # The worker's view of "computed" at dispatch time
                    # includes tokens still in flight on the device.
                    num_computed_tokens=req.num_computed_tokens
                    + req.num_inflight_tokens,
                    num_new_tokens=num_new,
                )
            )
            if drafts is not None:
                out.draft_token_ids[req.request_id] = drafts
                self.spec_drafted_tokens += len(drafts)
            if req.is_prefill:
                prefill_used += num_new
            else:
                req.num_inflight_tokens += num_new
            scheduled_running.append(req)

        # 2) admit waiting requests while budget and seats remain.
        while (
            self.waiting
            and token_budget > 0
            and len(self.running) < self.config.max_num_seqs
        ):
            req = self.waiting[0]
            if self.qos.enabled:
                # Priority admission (ISSUE 16): highest class first,
                # FIFO within a class (the deque IS arrival order).
                # Strict: a blocked high-class head blocks lower
                # classes too — borrowing happens at admission control,
                # not by reordering around a starved guarantee.
                req = self._pick_waiting()
            if req.request_id in preempted:
                break  # do not resume a request preempted this same step
            # Prefix cache: a request without pages resumes after the
            # longest cached page chain matching its tokens (pure query;
            # state changes only on actual admission below).  Covers
            # preemption-resume too — content addressing makes a
            # request's own earlier pages an ordinary hit.  With the
            # tiered radix index the hit may extend into the host-DRAM
            # tier: a host run at/above the restore crossover is
            # streamed back into fresh pages and counted as computed;
            # below it those tokens are simply recomputed.
            hit_tokens, hit_pages, plan, restore = 0, [], None, False
            if (
                self.enable_prefix_caching
                and req.num_computed_tokens == 0
                and not req.page_ids
            ):
                if self._tiered:
                    plan = self.allocator.plan_prefix(req)
                    restore = (
                        plan.host_tokens > 0
                        and plan.host_tokens
                        >= self.allocator.restore_min_tokens
                    )
                    hit_tokens = plan.resident_tokens + (
                        plan.host_tokens if restore else 0
                    )
                else:
                    hit_tokens, hit_pages = self.allocator.query_prefix(req)
            remaining_prompt = (
                req.prefill_target - req.num_computed_tokens - hit_tokens
            )
            num_new = min(remaining_prompt, token_budget)
            if (
                prefill_cap is not None
                and self._qos_priority(req) <= max_decode_prio
            ):
                num_new = min(num_new, prefill_cap - prefill_used)
            if num_new <= 0:
                break
            if not self.config.enable_chunked_prefill:
                if remaining_prompt > token_budget:
                    break
                num_new = remaining_prompt
            # Admission: don't preempt running requests for new ones.
            if plan is not None and hit_tokens:
                ok = self.allocator.can_admit_plan(plan, num_new, restore)
            elif hit_pages:
                ok = self.allocator.can_allocate_with_prefix(
                    hit_pages, hit_tokens + num_new
                )
            else:
                ok = self.allocator.can_allocate(req, num_new)
            if not ok:
                break
            self._waiting_pop(req, popleft=req is self.waiting[0])
            host_hit = 0
            try:
                if self.enable_prefix_caching and hit_tokens:
                    if plan is not None:
                        restored = self.allocator.attach_plan(
                            req, plan, restore
                        )
                        host_hit = restored * self.page_size
                        self.kv_restore_pages += restored
                    else:
                        self.allocator.attach_prefix(req, hit_pages)
                    # The chunked-prefill path resumes from here, so the
                    # model runner gets the partial prefill for free.
                    req.num_computed_tokens = hit_tokens
                new_pages = self.allocator.allocate(req, num_new)
            except NoFreePagesError:
                # The admission estimate can over-count free capacity in
                # a rare radix corner (an unreffed interior above a
                # reffed duplicate-content chain).  Roll back cleanly:
                # the request re-queues untouched and this schedule
                # stops admitting.
                self.allocator.free(req)
                req.num_computed_tokens = 0
                self._waiting_push(req, left=True)
                break
            if self.enable_prefix_caching:
                self.prefix_cache_queries += req.prefill_target
                self.prefix_cache_hits += hit_tokens
                self.prefix_cache_hits_host += host_hit
                req.metrics.cached_tokens = hit_tokens
            if req.status == RequestStatus.WAITING:
                req.metrics.first_scheduled_time = time.time()
                req.metrics.first_scheduled_time_mono = time.monotonic()
            resumed = req.status == RequestStatus.PREEMPTED
            req.status = RequestStatus.RUNNING
            self.running.append(req)
            out.num_scheduled_tokens[req.request_id] = num_new
            out.total_num_scheduled_tokens += num_new
            token_budget -= num_new
            if out.trace_ctx is None:
                out.trace_ctx = req.trace_ctx
            out.new_requests.append(
                NewRequestData(
                    req_id=req.request_id,
                    # On preemption-resume the worker state was dropped, so
                    # resend everything incl. generated tokens.
                    prompt_token_ids=req.all_token_ids
                    if resumed
                    else req.prompt_token_ids,
                    num_prompt_tokens=req.num_prompt_tokens,
                    page_ids=list(req.page_ids),
                    num_computed_tokens=req.num_computed_tokens,
                    num_new_tokens=num_new,
                    sampling_params=req.sampling_params,
                )
            )
            prefill_used += num_new

        # Tiered KV (ISSUE 14): ship the spill/restore spans this
        # schedule produced (evictions during allocate, restores during
        # attach) on this step — ahead of the step's own KV writes.
        if self._tiered:
            spill_ops, restore_ops = self.allocator.take_tier_ops()
            self.kv_spill_pages += len(spill_ops)
            if self._held_tier_ops is not None:
                held_s, held_r = self._held_tier_ops
                self._held_tier_ops = None
                spill_ops = held_s + spill_ops
                restore_ops = held_r + restore_ops
            if spill_ops or restore_ops:
                if out.is_empty:
                    # Empty outputs are never dispatched — hold the
                    # spans for the next step that reaches the workers.
                    self._held_tier_ops = (spill_ops, restore_ops)
                else:
                    out.kv_spill_ops = spill_ops
                    out.kv_restore_ops = restore_ops
                    # Slots consumed by shipped restores become
                    # reusable for FUTURE spill batches only.
                    self.allocator.release_shipped_slots()

        out.preempted_req_ids = sorted(preempted)
        if self._held_notices is not None:
            held_fin, held_pre = self._held_notices
            self._held_notices = None
            out.finished_req_ids = held_fin + out.finished_req_ids
            out.preempted_req_ids = held_pre + [
                p for p in out.preempted_req_ids if p not in held_pre
            ]
        if out.is_empty and (out.finished_req_ids or out.preempted_req_ids):
            # Empty outputs are never dispatched — hold the notices for
            # the next step that actually reaches the workers.
            self._held_notices = (
                out.finished_req_ids, out.preempted_req_ids
            )
            out.finished_req_ids = []
            out.preempted_req_ids = []
        return out

    def _propose_drafts(self) -> dict[str, list[int]]:
        """N-gram prompt-lookup proposals for an all-decode step
        (ISSUE 11).  Returns {} unless spec decode is enabled, every
        running request is spec-eligible (greedy, no penalties — the
        gate is batch-wide so one compiled verify program serves the
        step), the pipeline is drained (the proposer and the verify
        input need the host-current last token), and at least one
        request found a draftable tail n-gram."""
        if self.spec is None:
            return {}
        if any(
            not spec_eligible(r.sampling_params) for r in self.running
        ):
            return {}
        if any(r.num_inflight_tokens > 0 for r in self.running):
            # Pipelined continuation (spec dormant): host tokens are
            # stale, so no proposals — count toward the probe cadence.
            self._spec_pipeline_steps += 1
            return {}
        self._spec_pipeline_steps = 0
        drafts: dict[str, list[int]] = {}
        for r in self.running:
            room = (
                min(r.max_total_tokens, self.config.max_model_len)
                - r.num_tokens
            )
            if room <= 1:
                continue  # no space for a draft beyond the bonus token
            d = self.spec.propose(r.token_history(), room - 1)
            if d:
                drafts[r.request_id] = d
        if drafts:
            self._spec_dry_streak = 0
        else:
            self._spec_dry_streak += 1
        return drafts

    def spec_wants_sync(self) -> bool:
        """Pipelining hysteresis (ISSUE 11): True while the engine
        should drain dispatches before each schedule so the proposer
        sees host-current tokens.  While prompt-lookup keeps drafting,
        the verify pass is the latency hider and every step runs
        synchronously; after ``_SPEC_DRY_LIMIT`` consecutive draftless
        eligible schedules the engine resumes the async dispatch
        pipeline (spec dormant — non-repetitive greedy traffic keeps
        the PR 6 overlap instead of silently regressing below the
        spec-off baseline), draining once every
        ``_SPEC_PROBE_INTERVAL`` pipelined schedules to re-probe for
        drafts.  Pure read: call sites may invoke it multiple times
        per step."""
        if self._spec_dry_streak < _SPEC_DRY_LIMIT:
            return True
        return self._spec_pipeline_steps >= _SPEC_PROBE_INTERVAL

    def _pick_waiting(self) -> Request:
        """QoS admission order (ISSUE 16): the highest-priority class's
        oldest waiting request.  A forward scan keeping the FIRST max
        preserves FIFO within each class, so equal-priority traffic
        behaves exactly like the seed deque."""
        best = self.waiting[0]
        best_prio = self._qos_priority(best)
        for cand in self.waiting:
            p = self._qos_priority(cand)
            if p > best_prio:
                best, best_prio = cand, p
        return best

    def _allocate_or_preempt(
        self,
        req: Request,
        num_new: int,
        preempted: set[str],
        scheduled_this_step: list[Request],
    ) -> tuple[bool, list[int]] | None:
        """Allocate pages for req, evicting lower-priority running requests
        if needed. Returns (True, new_pages) or None if req itself could not
        be scheduled (it was preempted).

        A request already scheduled this step must never be chosen as the
        victim: its page ids are already baked into the SchedulerOutput, so
        freeing them would hand the same pages to two requests.
        """
        while True:
            try:
                return True, self.allocator.allocate(req, num_new)
            except NoFreePagesError:
                victim = self._pick_victim(
                    req, preempted, scheduled_this_step
                )
                if victim is None:
                    # Preempt req itself.
                    self._preempt(req, preempted)
                    return None
                self._preempt(victim, preempted)

    def _pick_victim(
        self,
        req: Request,
        preempted: set[str],
        scheduled_this_step: list[Request],
    ) -> Request | None:
        """Eviction victim for req's allocation.  Seed policy: the most
        recently admitted eligible request.  Under QoS (ISSUE 16) the
        LOWEST class goes first (recency breaks ties within a class),
        and a victim of strictly higher class than the requester is
        never evicted — the requester yields instead, so low-class
        pressure can't thrash high-class decodes."""
        if not self.qos.enabled:
            for cand in reversed(self.running):
                if (
                    cand is not req
                    and cand.request_id not in preempted
                    and cand not in scheduled_this_step
                ):
                    return cand
            return None
        victim = None
        victim_prio = 0
        for idx, cand in enumerate(self.running):
            if (
                cand is req
                or cand.request_id in preempted
                or cand in scheduled_this_step
            ):
                continue
            p = self._qos_priority(cand)
            if victim is None or p < victim_prio:
                victim, victim_prio = cand, p
            elif p == victim_prio:
                victim = cand  # later index: recency within the class
        if victim is not None and victim_prio > self._qos_priority(req):
            return None
        return victim

    def _preempt(self, req: Request, preempted: set[str]) -> None:
        logger.debug("preempting request %s", req.request_id)
        self.num_preemptions += 1
        req.num_preemptions += 1
        get_tracer().event(
            req.trace_ctx,
            "engine.preempted",
            request_id=req.request_id,
            num_tokens=req.num_tokens,
        )
        self.allocator.free(req)
        req.num_computed_tokens = 0
        # In-flight sampled tokens are lost on preemption; the request
        # re-prefills to what the host has and regenerates (same PRNG
        # stream position, so seeded sampling is unaffected).
        req.num_inflight_tokens = 0
        req.resume_target = req.num_tokens
        if req in self.running:
            self.running.remove(req)
        # Workers drop state on preempted_req_ids in this step's output;
        # no entry in _finished_since_last (it would collide with the
        # request's own resume in a later step's new_requests).
        preempted.add(req.request_id)
        if self.qos.enabled:
            cls = self._qos_bucket(req)
            self.preemptions_by_class[cls] = (
                self.preemptions_by_class.get(cls, 0) + 1
            )
        shed_after = self.config.preempt_shed_threshold
        if shed_after > 0 and self.qos.enabled:
            # Preemption weight scales the shed budget: a 0.5-weight
            # class degrades to rejection after half the evictions, a
            # 2.0-weight class rides out twice as many.
            shed_after = max(
                int(
                    round(
                        shed_after
                        * self.qos.resolve(
                            req.sampling_params.slo_class
                        ).preemption_weight
                    )
                ),
                1,
            )
        if shed_after > 0 and req.num_preemptions > shed_after:
            # Sustained-pressure preempt-to-shed (ISSUE 8): this request
            # has been evicted-and-recomputed past the policy budget —
            # another resume would just thrash the allocator.  Degrade
            # to a rejection: finish with finish_reason="overloaded"
            # and partial output instead of re-queueing.  The worker
            # drop-notice already rides preempted_req_ids above.
            req.status = RequestStatus.FINISHED_SHED
            self.requests.pop(req.request_id, None)
            self._finished_out_of_band.append(req)
            self.num_sheds += 1
            if self.qos.enabled:
                cls = self._qos_bucket(req)
                self.sheds_by_class[cls] = (
                    self.sheds_by_class.get(cls, 0) + 1
                )
            get_tracer().event(
                req.trace_ctx,
                "engine.preempt_shed",
                request_id=req.request_id,
                num_preemptions=req.num_preemptions,
                num_output_tokens=req.num_output_tokens,
            )
            return
        req.status = RequestStatus.PREEMPTED
        self._waiting_push(req, left=True)

    # ---- post-step bookkeeping ----
    def update_from_output(
        self,
        scheduler_output: SchedulerOutput,
        sampled_token_ids: dict[str, list[int]],
    ) -> list[Request]:
        """Advance request states given the tokens the workers sampled.
        Returns requests that finished this step."""
        finished: list[Request] = []
        for req_id, num in scheduler_output.num_scheduled_tokens.items():
            deferred = self._deferred_frees.get(req_id)
            if deferred is not None:
                # A step scheduled before this request finished is
                # draining: settle its in-flight debt, free the pages
                # once the last such step lands.
                deferred.num_inflight_tokens = max(
                    deferred.num_inflight_tokens - num, 0
                )
                if deferred.num_inflight_tokens == 0:
                    del self._deferred_frees[req_id]
                    self.allocator.free(deferred)
                continue
            req = self.requests.get(req_id)
            if req is None or req.status != RequestStatus.RUNNING:
                continue  # aborted mid-step
            new_tokens = sampled_token_ids.get(req_id, [])
            if req_id in scheduler_output.draft_token_ids:
                # Spec verify pass (ISSUE 11): the window was scheduled
                # at its full width (input + all drafts) but KV is only
                # valid through the accepted prefix — advance by the
                # EMITTED count (1 + accepted drafts); the rejected-draft
                # rows are garbage the next window overwrites in place
                # (block_manager.register_computed never reaches them).
                num_adv = len(new_tokens)
                self.spec_accepted_tokens += max(len(new_tokens) - 1, 0)
            else:
                num_adv = num
            req.num_computed_tokens += num_adv
            req.num_inflight_tokens = max(req.num_inflight_tokens - num, 0)
            for tok in new_tokens:
                req.append_output_token(tok)
                status = req.check_stop(self.config.max_model_len)
                if status is not None:
                    req.status = status
                    break
            if self.enable_prefix_caching:
                # Pages fully covered by computed tokens now hold valid
                # KV: register them (before any free below, so a
                # finishing request's pages enter the LRU registered).
                self.allocator.register_computed(req)
            if req.status.is_finished:
                self.running.remove(req)
                self._release_or_defer(req)
                self._finished_since_last.append(req_id)
                finished.append(req)
                del self.requests[req_id]
        return finished

    def finish_request(self, req: Request, status: RequestStatus) -> None:
        req.status = status
        if req in self.running:
            self.running.remove(req)
            self._finished_since_last.append(req.request_id)
        if req in self.waiting:
            self._waiting_pop(req)
        self._release_or_defer(req)
        self.requests.pop(req.request_id, None)
