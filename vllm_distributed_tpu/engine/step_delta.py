"""Delta-compressed step frames (ISSUE 7 tentpole piece 2).

``SchedulerOutput`` is already delta-shaped at the object level (full
data only for newly-admitted requests, per-step deltas for cached ones),
but its WIRE form still repeats every request id string in four places
per step (``cached_requests``, ``num_scheduled_tokens`` keys,
finished/preempted lists) and re-ships ``num_computed_tokens`` that the
worker can derive itself.  At batch 64 that is the dominant per-step
payload — O(batch) strings plus dataclass framing — serialized once per
host per step on the driver's hot path.

This module compresses a step to a ``StepFrame``:

- every request gets a small integer index at admission
  (``NewRequestData`` rides the frame verbatim — prompt ids, block
  table, sampling params are sent ONCE, the SGLang/vLLM worker-mirror
  economy);
- per-step entries for cached requests carry only ``(index,
  new_token_count, block_table_appends)``;
- finished/resumed/preempted notices are index lists;
- ``num_computed_tokens``, ``num_scheduled_tokens`` and the step total
  are DERIVED, not shipped: the worker-side ``StepStateMirror`` advances
  its per-request token counter by each step's new-token count, exactly
  mirroring the scheduler's ``num_computed + num_inflight`` arithmetic.

``StepDeltaEncoder.encode`` (driver) and ``StepStateMirror.decode``
(worker) are exact inverses: the reconstructed ``SchedulerOutput``
compares equal to the original, field for field, including dict
ordering — asserted by the round-trip property tests in
tests/test_step_delta.py.  The encoder also self-checks its computed
prediction against the scheduler's value each step and falls back to an
explicit override (``computed_overrides``) on mismatch, so a prediction
bug degrades to a larger frame, never to silent state divergence.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from vllm_distributed_tpu.engine.scheduler import (
    CachedRequestData,
    NewRequestData,
    SchedulerOutput,
)
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


@dataclass
class StepFrame:
    """One step's delta-compressed wire form (pickled ONCE per step and
    shared byte-identically across every host send)."""

    step_id: int
    decode_steps: int = 1
    # True = the driver blocks on this step's result (prefill/mixed
    # steps); the worker runs it inline instead of two-phase.
    blocking: bool = False
    # Admissions (and preemption-resumes): full request state, once.
    new: list[NewRequestData] = field(default_factory=list)
    # (index, num_new_tokens, new_page_ids) per already-mirrored request.
    cached: list[tuple[int, int, list[int]]] = field(default_factory=list)
    finished: list[int] = field(default_factory=list)
    preempted: list[int] = field(default_factory=list)
    # index -> absolute num_computed_tokens; normally empty (see module
    # docstring), populated only if the encoder's prediction disagrees
    # with the scheduler.
    computed_overrides: dict[int, int] = field(default_factory=dict)
    # ---- speculative decoding (ISSUE 11) ----
    # index -> draft tokens to verify this step (marks the cached entry
    # as a spec verify window: num_new = 1 + len(drafts), the ACTUAL
    # advance is a device result this frame cannot know)...
    drafts: dict[int, list[int]] = field(default_factory=dict)
    # ...so the NEXT frame that touches the request ships the realized
    # advance (index -> 1 + accepted drafts), applied by the mirror
    # before its cached entries — both sides advance by the same value
    # without a prediction, keeping lockstep without override warnings.
    spec_advance: dict[int, int] = field(default_factory=dict)
    # ---- tiered KV cache (ISSUE 14) ----
    # (hbm_page, host_slot) / (host_slot, hbm_page) spans the worker
    # applies IN FRAME ORDER before executing the step (spills first,
    # then restores) — page ids and slot ids are already worker-global,
    # so they ship verbatim.
    spills: list[tuple[int, int]] = field(default_factory=list)
    restores: list[tuple[int, int]] = field(default_factory=list)
    trace_ctx: tuple | None = None
    # Escape hatch: a SchedulerOutput the codec cannot synthesize from
    # mirror state (num_scheduled_tokens entries with no matching
    # new/cached record — hand-built test payloads, not scheduler
    # output) ships verbatim and bypasses the mirror entirely.
    raw: SchedulerOutput | None = None


class _Entry:
    __slots__ = ("req_id", "computed", "spec_pending")

    def __init__(self, req_id: str, computed: int) -> None:
        self.req_id = req_id
        self.computed = computed
        # Width of the last spec verify window scheduled for this
        # request (0 = none pending): the encoder leaves `computed` at
        # the window base until the realized advance is known.
        self.spec_pending = 0


class StepDeltaEncoder:
    """Driver-side: assigns request indices and emits ``StepFrame``s.
    Stateful — every dispatched step MUST flow through one encoder
    instance, in order, or the worker mirrors desynchronize (the
    executor enforces this by routing all step traffic through the
    stream path whenever it is enabled)."""

    def __init__(self) -> None:
        self._by_id: dict[str, _Entry] = {}
        self._index: dict[str, int] = {}
        self._next_index = 0

    def _assign(self, req_id: str) -> int:
        idx = self._next_index
        self._next_index += 1
        self._index[req_id] = idx
        return idx

    def encode(
        self, so: SchedulerOutput, *, blocking: bool = False
    ) -> StepFrame:
        covered = {c.req_id for c in so.cached_requests} | {
            n.req_id for n in so.new_requests
        }
        if covered != set(so.num_scheduled_tokens):
            # Not a scheduler-produced step (every scheduled request is
            # paired with a new/cached record there) — ship it raw.
            logger.debug(
                "step %d not delta-encodable; shipping raw", so.step_id
            )
            return StepFrame(
                step_id=so.step_id,
                decode_steps=so.decode_steps,
                blocking=blocking,
                trace_ctx=so.trace_ctx,
                raw=so,
            )
        frame = StepFrame(
            step_id=so.step_id,
            decode_steps=so.decode_steps,
            blocking=blocking,
            trace_ctx=so.trace_ctx,
            spills=list(so.kv_spill_ops),
            restores=list(so.kv_restore_ops),
        )
        # Order mirrors the worker's apply order (model_runner
        # _apply_scheduler_deltas): finished/preempted drop state before
        # admissions may reuse a request id.
        for rid in so.finished_req_ids:
            idx = self._index.pop(rid, None)
            if idx is None:
                raise ValueError(f"finish notice for unknown request {rid}")
            self._by_id.pop(rid, None)
            frame.finished.append(idx)
        for rid in so.preempted_req_ids:
            idx = self._index.pop(rid, None)
            if idx is None:
                raise ValueError(f"preempt notice for unknown request {rid}")
            self._by_id.pop(rid, None)
            frame.preempted.append(idx)
        for c in so.cached_requests:
            entry = self._by_id.get(c.req_id)
            idx = self._index.get(c.req_id)
            if entry is None or idx is None:
                raise ValueError(
                    f"cached delta for unmirrored request {c.req_id}"
                )
            if entry.spec_pending:
                # The realized advance of the last spec window (1 +
                # accepted drafts) is now visible in the scheduler's
                # computed value; ship it so the mirror advances by the
                # same amount.  Out-of-range values fall through to the
                # override path below.
                adv = c.num_computed_tokens - entry.computed
                if 1 <= adv <= entry.spec_pending:
                    frame.spec_advance[idx] = adv
                    entry.computed = c.num_computed_tokens
                entry.spec_pending = 0
            if entry.computed != c.num_computed_tokens:
                # Prediction miss: ship the absolute value this step (a
                # bigger frame, never a divergent mirror) and resync.
                logger.warning(
                    "step %d: computed-token prediction for %s is %d, "
                    "scheduler says %d — shipping explicit override",
                    so.step_id,
                    c.req_id,
                    entry.computed,
                    c.num_computed_tokens,
                )
                frame.computed_overrides[idx] = c.num_computed_tokens
                entry.computed = c.num_computed_tokens
            frame.cached.append((idx, c.num_new_tokens, c.new_page_ids))
            d = so.draft_token_ids.get(c.req_id)
            if d is not None:
                # Spec verify window: the advance is a device result;
                # hold `computed` at the base until the next frame ships
                # spec_advance (see above).
                frame.drafts[idx] = list(d)
                entry.spec_pending = c.num_new_tokens
            else:
                entry.computed += c.num_new_tokens
        for nr in so.new_requests:
            if nr.req_id in self._index:
                raise ValueError(f"re-admission of mirrored {nr.req_id}")
            self._assign(nr.req_id)
            self._by_id[nr.req_id] = _Entry(
                nr.req_id, nr.num_computed_tokens + nr.num_new_tokens
            )
            frame.new.append(nr)
        return frame

    @property
    def num_mirrored(self) -> int:
        return len(self._by_id)


class StepStateMirror:
    """Worker-side inverse: reconstructs the full ``SchedulerOutput``
    from a ``StepFrame``.  One mirror per worker host; every host
    receives every frame in step order, so all mirrors (and the
    driver-side encoder) stay in lockstep."""

    def __init__(self) -> None:
        self._by_index: dict[int, _Entry] = {}
        self._next_index = 0

    def decode(self, frame: StepFrame) -> SchedulerOutput:
        if frame.raw is not None:
            return frame.raw
        so = SchedulerOutput(
            step_id=frame.step_id,
            decode_steps=frame.decode_steps,
            trace_ctx=(
                tuple(frame.trace_ctx)
                if frame.trace_ctx is not None
                else None
            ),
            kv_spill_ops=[tuple(s) for s in frame.spills],
            kv_restore_ops=[tuple(r) for r in frame.restores],
        )
        for idx in frame.finished:
            entry = self._by_index.pop(idx)
            so.finished_req_ids.append(entry.req_id)
        for idx in frame.preempted:
            entry = self._by_index.pop(idx)
            so.preempted_req_ids.append(entry.req_id)
        # Realized spec-window advances land before this frame's cached
        # entries read `computed` (encoder symmetry: it reconciled the
        # same requests before encoding their new entries).
        for idx, adv in frame.spec_advance.items():
            self._by_index[idx].computed += adv
        for idx, num_new, new_page_ids in frame.cached:
            entry = self._by_index[idx]
            override = frame.computed_overrides.get(idx)
            if override is not None:
                entry.computed = override
            so.cached_requests.append(
                CachedRequestData(
                    req_id=entry.req_id,
                    new_page_ids=list(new_page_ids),
                    num_computed_tokens=entry.computed,
                    num_new_tokens=num_new,
                )
            )
            d = frame.drafts.get(idx)
            if d is not None:
                # Spec verify window: the worker's runner computes the
                # realized advance itself; `computed` stays at the base
                # until the next frame's spec_advance.
                so.draft_token_ids[entry.req_id] = list(d)
            else:
                entry.computed += num_new
            so.num_scheduled_tokens[entry.req_id] = num_new
            so.total_num_scheduled_tokens += num_new
        for nr in frame.new:
            self._by_index[self._next_index] = _Entry(
                nr.req_id, nr.num_computed_tokens + nr.num_new_tokens
            )
            self._next_index += 1
            so.new_requests.append(nr)
            so.num_scheduled_tokens[nr.req_id] = nr.num_new_tokens
            so.total_num_scheduled_tokens += nr.num_new_tokens
        return so

    @property
    def num_mirrored(self) -> int:
        return len(self._by_index)
