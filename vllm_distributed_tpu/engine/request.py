"""Per-request state tracked by the scheduler/engine."""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field

from vllm_distributed_tpu.outputs import RequestMetrics
from vllm_distributed_tpu.sampling_params import SamplingParams


class RequestStatus(enum.Enum):
    WAITING = enum.auto()
    RUNNING = enum.auto()
    PREEMPTED = enum.auto()
    FINISHED_STOPPED = enum.auto()
    FINISHED_LENGTH = enum.auto()
    FINISHED_ABORTED = enum.auto()

    @property
    def is_finished(self) -> bool:
        return self in (
            RequestStatus.FINISHED_STOPPED,
            RequestStatus.FINISHED_LENGTH,
            RequestStatus.FINISHED_ABORTED,
        )


FINISH_REASON = {
    RequestStatus.FINISHED_STOPPED: "stop",
    RequestStatus.FINISHED_LENGTH: "length",
    RequestStatus.FINISHED_ABORTED: "abort",
}


@dataclass(eq=False)
class Request:
    request_id: str
    prompt_token_ids: list[int]
    sampling_params: SamplingParams
    prompt: str | None = None
    eos_token_id: int | None = None
    arrival_time: float = field(default_factory=time.monotonic)

    status: RequestStatus = RequestStatus.WAITING
    # All tokens = prompt + generated output.
    output_token_ids: list[int] = field(default_factory=list)
    # How many tokens have had their KV computed (chunked prefill cursor).
    num_computed_tokens: int = 0
    # Decode tokens scheduled to the device but whose sampled results have
    # not been applied yet (engine pipelining: dispatch N+1 can be issued
    # before N's tokens arrive; the device scan carries the real values).
    num_inflight_tokens: int = 0
    # Page ids owned by this request, in order.
    page_ids: list[int] = field(default_factory=list)
    # After preemption-resume, KV for already-generated tokens must be
    # recomputed too; this is the token count to re-prefill up to.
    resume_target: int = 0
    metrics: RequestMetrics = field(default_factory=RequestMetrics)
    stop_reason: int | str | None = None
    # Cumulative logprobs bookkeeping (filled only when requested).
    logprobs: list[dict[int, float]] | None = None
    cumulative_logprob: float = 0.0
    # (trace_id, span_id) of the caller's root span (tracing.py); the
    # engine parents this request's queue/prefill/decode spans and
    # preemption/replay events to it.  None = untraced.
    trace_ctx: tuple | None = None

    def __post_init__(self) -> None:
        self.metrics.arrival_time = time.time()
        self.metrics.arrival_time_mono = self.arrival_time
        if self.sampling_params.logprobs is not None:
            self.logprobs = []

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_token_ids)

    @property
    def num_output_tokens(self) -> int:
        return len(self.output_token_ids)

    @property
    def num_tokens(self) -> int:
        return self.num_prompt_tokens + self.num_output_tokens

    @property
    def all_token_ids(self) -> list[int]:
        return self.prompt_token_ids + self.output_token_ids

    @property
    def prefill_target(self) -> int:
        """Tokens whose KV is recomputed in (chunked) prefill before decode
        resumes: the prompt, or everything known at preemption time."""
        return max(self.num_prompt_tokens, self.resume_target)

    @property
    def is_prefill(self) -> bool:
        return self.num_computed_tokens < self.prefill_target

    @property
    def max_total_tokens(self) -> int:
        mt = self.sampling_params.max_tokens
        if mt is None:
            return 1 << 60
        return self.num_prompt_tokens + mt

    def append_output_token(self, token_id: int) -> None:
        self.output_token_ids.append(token_id)

    def check_stop(self, max_model_len: int) -> RequestStatus | None:
        """Returns a finished status if the request should stop, else None.
        Stop-string checking happens in the detokenizer, not here."""
        sp = self.sampling_params
        if self.num_output_tokens >= sp.min_tokens:
            last = self.output_token_ids[-1] if self.output_token_ids else None
            if (
                not sp.ignore_eos
                and self.eos_token_id is not None
                and last == self.eos_token_id
            ):
                self.stop_reason = None
                return RequestStatus.FINISHED_STOPPED
            if last is not None and last in sp.stop_token_ids:
                self.stop_reason = last
                return RequestStatus.FINISHED_STOPPED
        if self.num_tokens >= min(self.max_total_tokens, max_model_len):
            return RequestStatus.FINISHED_LENGTH
        return None
