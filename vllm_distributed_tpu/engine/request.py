"""Per-request state tracked by the scheduler/engine."""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field

from vllm_distributed_tpu.outputs import RequestMetrics
from vllm_distributed_tpu.sampling_params import SamplingParams


class RequestStatus(enum.Enum):
    WAITING = enum.auto()
    RUNNING = enum.auto()
    PREEMPTED = enum.auto()
    FINISHED_STOPPED = enum.auto()
    FINISHED_LENGTH = enum.auto()
    FINISHED_ABORTED = enum.auto()
    # Deadline expired: shed from waiting before prefill, or stopped
    # mid-decode with partial output (ISSUE 8).
    FINISHED_TIMEOUT = enum.auto()
    # Shed by the sustained-pressure preempt-to-shed policy: repeated
    # preemption under load degrades to a rejection, not allocator
    # thrash (ISSUE 8).
    FINISHED_SHED = enum.auto()

    @property
    def is_finished(self) -> bool:
        return self in (
            RequestStatus.FINISHED_STOPPED,
            RequestStatus.FINISHED_LENGTH,
            RequestStatus.FINISHED_ABORTED,
            RequestStatus.FINISHED_TIMEOUT,
            RequestStatus.FINISHED_SHED,
        )


FINISH_REASON = {
    RequestStatus.FINISHED_STOPPED: "stop",
    RequestStatus.FINISHED_LENGTH: "length",
    RequestStatus.FINISHED_ABORTED: "abort",
    RequestStatus.FINISHED_TIMEOUT: "timeout",
    RequestStatus.FINISHED_SHED: "overloaded",
}


@dataclass(eq=False)
class Request:
    request_id: str
    prompt_token_ids: list[int]
    sampling_params: SamplingParams
    prompt: str | None = None
    eos_token_id: int | None = None
    arrival_time: float = field(default_factory=time.monotonic)

    status: RequestStatus = RequestStatus.WAITING
    # All tokens = prompt + generated output.
    output_token_ids: list[int] = field(default_factory=list)
    # How many tokens have had their KV computed (chunked prefill cursor).
    num_computed_tokens: int = 0
    # Decode tokens scheduled to the device but whose sampled results have
    # not been applied yet (engine pipelining: dispatch N+1 can be issued
    # before N's tokens arrive; the device scan carries the real values).
    num_inflight_tokens: int = 0
    # Page ids owned by this request, in order.
    page_ids: list[int] = field(default_factory=list)
    # After preemption-resume, KV for already-generated tokens must be
    # recomputed too; this is the token count to re-prefill up to.
    resume_target: int = 0
    metrics: RequestMetrics = field(default_factory=RequestMetrics)
    stop_reason: int | str | None = None
    # Cumulative logprobs bookkeeping (filled only when requested).
    logprobs: list[dict[int, float]] | None = None
    cumulative_logprob: float = 0.0
    # (trace_id, span_id) of the caller's root span (tracing.py); the
    # engine parents this request's queue/prefill/decode spans and
    # preemption/replay events to it.  None = untraced.
    trace_ctx: tuple | None = None
    # Monotonic instant this request must be finished by (ISSUE 8);
    # None = no deadline.  Set by LLMEngine.add_request from the
    # client's deadline_ms or the server default; checked cheaply at
    # schedule time.
    deadline_mono: float | None = None
    # Times this request was preempted (the preempt-to-shed policy's
    # thrash signal).
    num_preemptions: int = 0
    # Incrementally-maintained prompt+output concat (token_history);
    # None until first use.
    _hist: list[int] | None = field(default=None, repr=False)

    def __post_init__(self) -> None:
        self.metrics.arrival_time = time.time()
        self.metrics.arrival_time_mono = self.arrival_time
        # SLO class rides on RequestMetrics so the metrics layer can key
        # its per-class accounting without reaching back into params.
        self.metrics.slo_class = self.sampling_params.slo_class
        if self.sampling_params.logprobs is not None:
            self.logprobs = []

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_token_ids)

    @property
    def num_output_tokens(self) -> int:
        return len(self.output_token_ids)

    @property
    def num_tokens(self) -> int:
        return self.num_prompt_tokens + self.num_output_tokens

    @property
    def all_token_ids(self) -> list[int]:
        return self.prompt_token_ids + self.output_token_ids

    def token_history(self) -> list[int]:
        """Prompt+output as ONE list, maintained incrementally.

        The spec-decode proposer scans this every all-decode schedule;
        rebuilding the ``all_token_ids`` concat per request per step
        would put an O(context) copy on the scheduler hot path.  The
        cache extends by the appended delta (outputs only append
        between calls) and rebuilds outright when the output shrank
        (stop-string truncation).  Callers must treat the result as
        read-only."""
        want = self.num_prompt_tokens + self.num_output_tokens
        h = self._hist
        if h is None or len(h) > want:
            h = self.prompt_token_ids + self.output_token_ids
            self._hist = h
        elif len(h) < want:
            h.extend(
                self.output_token_ids[len(h) - self.num_prompt_tokens :]
            )
        return h

    @property
    def prefill_target(self) -> int:
        """Tokens whose KV is recomputed in (chunked) prefill before decode
        resumes: the prompt, or everything known at preemption time."""
        return max(self.num_prompt_tokens, self.resume_target)

    @property
    def is_prefill(self) -> bool:
        return self.num_computed_tokens < self.prefill_target

    @property
    def max_total_tokens(self) -> int:
        mt = self.sampling_params.max_tokens
        if mt is None:
            return 1 << 60
        return self.num_prompt_tokens + mt

    def set_deadline(self, default_deadline_ms: int) -> None:
        """Resolve the effective deadline: the client's deadline_ms
        sampling param, else the server default (0 = none).  Anchored
        to the monotonic arrival instant so NTP steps can't expire (or
        resurrect) a request."""
        ms = self.sampling_params.deadline_ms
        if ms is None and default_deadline_ms > 0:
            ms = default_deadline_ms
        if ms is not None:
            self.deadline_mono = self.arrival_time + ms / 1000.0

    def expired(self, now_mono: float) -> bool:
        return self.deadline_mono is not None and now_mono >= self.deadline_mono

    def append_output_token(self, token_id: int) -> None:
        self.output_token_ids.append(token_id)

    def check_stop(self, max_model_len: int) -> RequestStatus | None:
        """Returns a finished status if the request should stop, else None.
        Stop-string checking happens in the detokenizer, not here."""
        sp = self.sampling_params
        if self.num_output_tokens >= sp.min_tokens:
            last = self.output_token_ids[-1] if self.output_token_ids else None
            if (
                not sp.ignore_eos
                and self.eos_token_id is not None
                and last == self.eos_token_id
            ):
                self.stop_reason = None
                return RequestStatus.FINISHED_STOPPED
            if last is not None and last in sp.stop_token_ids:
                self.stop_reason = last
                return RequestStatus.FINISHED_STOPPED
        if self.num_tokens >= min(self.max_total_tokens, max_model_len):
            return RequestStatus.FINISHED_LENGTH
        return None
