"""Model construction + HF safetensors → sharded jax arrays.

The TPU analog of the reference's `collective_rpc("load_model")` step
(launch.py:292, SURVEY.md §5.4): weights come from a local HF snapshot
(safetensors shards), are read tensor-by-tensor on host, mapped through
the model's ``map_hf_name`` table, and placed onto the device mesh with
the model's ``partition_specs`` — each host materializes only its own
shard bytes when a mesh is given (host-parallel load).
"""

from __future__ import annotations

import glob
import os
import time
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.models import get_model_class

logger = init_logger(__name__)


def resolve_model_dir(model: str) -> str:
    """Local dir, or an HF-hub snapshot already present in the cache."""
    if os.path.isdir(model):
        return model
    cache = os.environ.get(
        "HF_HUB_CACHE",
        os.path.join(
            os.environ.get(
                "HF_HOME", os.path.expanduser("~/.cache/huggingface")
            ),
            "hub",
        ),
    )
    repo_dir = os.path.join(cache, "models--" + model.replace("/", "--"))
    snapshots = sorted(glob.glob(os.path.join(repo_dir, "snapshots", "*")))
    if snapshots:
        return snapshots[-1]
    raise FileNotFoundError(
        f"model {model!r} is neither a local directory nor a cached HF "
        f"snapshot (no network egress; pre-download weights)"
    )


def _set_path(tree: dict, path: tuple, value) -> None:
    node = tree
    for key in path[:-1]:
        if isinstance(key, int):
            node = node[key]
        else:
            node = node.setdefault(key, {})
    node[path[-1]] = value


def _spec_for(path: tuple, specs: dict | None) -> P:
    if specs is None:
        return P()
    node: Any = specs
    try:
        for key in path:
            node = node[key]
        return node
    except (KeyError, IndexError, TypeError):
        return P()


def _in_dim_shards(spec: P, mesh: Mesh | None, ndim: int) -> int:
    """Mesh shards along the weight's input (second-to-last) dim."""
    from vllm_distributed_tpu.ops.quant import axis_shards

    if mesh is None:
        return 1
    t = tuple(spec)
    pos = ndim - 2
    if pos < 0 or pos >= len(t) or t[pos] is None:
        return 1
    return axis_shards(t[pos], mesh)


def _quantize_and_place(model, tensor, spec: P, mesh: Mesh | None, dtype):
    """Weight-only quantize one tensor and shard its q/scale parts.

    int4 group boundaries align with the deployment's tp shards (so the
    grouped dequant reshape never crosses devices in the decode hot
    path).  This makes int4 grouping a function of the tp layout — like
    an AWQ checkpoint generated for a target config, int4 outputs agree
    across tp sizes within quantization tolerance, not bit-for-bit
    (int8 is layout-independent and bit-identical across tp)."""
    from vllm_distributed_tpu.ops.quant import (
        pick_group_size,
        pick_matmul_mode,
        place_quantized,
        quantize,
    )

    bits = 8 if model.quant_method == "int8" else 4
    group = 0
    if bits == 4:
        group = pick_group_size(
            tensor.shape[-2], _in_dim_shards(spec, mesh, tensor.ndim)
        )
    qt = quantize(
        tensor,
        bits,
        group,
        dtype=dtype,
        matmul=pick_matmul_mode(model.quant_method),
    )
    if mesh is not None:
        qt = place_quantized(qt, spec, mesh)
    return qt


def _place_tree(model, params, specs, mesh: Mesh | None):
    """Recursive device placement for an in-memory param tree (dummy
    init), quantizing the model's QUANT_PARAMS leaves when configured."""
    quant = getattr(model, "quant_method", None)

    def rec(p, s, path):
        # Containers are drained as they are processed (entries nulled
        # right after use) so original full-precision device arrays free
        # eagerly — otherwise quantizing a model that nearly fills HBM
        # peaks at original + quantized and OOMs (e.g. 7B bf16 on v5e).
        if isinstance(p, dict):
            out = {}
            for k in list(p):
                out[k] = rec(
                    p[k],
                    s.get(k) if isinstance(s, dict) else None,
                    path + (k,),
                )
                p[k] = None
            return out
        if isinstance(p, list):
            out_list = []
            for i in range(len(p)):
                out_list.append(
                    rec(
                        p[i],
                        s[i] if isinstance(s, (list, tuple)) else None,
                        path + (i,),
                    )
                )
                p[i] = None
            return out_list
        if s is None and specs is not None:
            # partition_specs() drifted from init_params(): loading a
            # weight fully replicated at scale is a silent perf/memory
            # bug, so make the drift visible.
            logger.warning(
                "no partition spec for param %r; replicating", path
            )
        spec = s if s is not None else P()
        if quant and model.should_quantize(path):
            return _quantize_and_place(model, p, spec, mesh, model.dtype)
        if mesh is not None:
            return jax.device_put(p, NamedSharding(mesh, spec))
        return p

    return rec(params, specs, ())


def load_hf_weights(
    model,
    model_dir: str,
    *,
    mesh: Mesh | None = None,
    dtype: Any = None,
) -> dict:
    """Stream every tensor of every *.safetensors shard into the param
    tree.  Layer-norm/bias params keep float32 precision headroom is not
    needed — everything is cast to the model dtype."""
    from safetensors import safe_open

    dtype = jnp.dtype(dtype or model.dtype)
    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {model_dir}")
    # load_specs (when present) describes per-tensor placement DURING the
    # load, which can differ from the final partition_specs — e.g. MoE
    # expert tensors arrive unstacked and are stacked by finalize_params.
    if hasattr(model, "load_specs"):
        specs = model.load_specs()
    elif hasattr(model, "partition_specs"):
        specs = model.partition_specs()
    else:
        specs = None

    params: dict = {"layers": [{} for _ in range(model.num_layers)]}
    start = time.monotonic()
    n = 0
    cpu = jax.devices("cpu")[0]
    quant = getattr(model, "quant_method", None)
    for file in files:
        with safe_open(file, framework="flax") as f:
            for name in f.keys():
                mapped = model.map_hf_name(name)
                if mapped is None:
                    continue
                path, transform = mapped
                with jax.default_device(cpu):
                    tensor = f.get_tensor(name)
                    if transform == "T":
                        tensor = tensor.T
                    tensor = tensor.astype(dtype)
                spec = _spec_for(path, specs) if mesh is not None else P()
                if quant and model.should_quantize(path):
                    # Quantize per tensor DURING the stream so the full-
                    # precision model never materializes (the point of
                    # weight-only quant: 70B-class fits v5e HBM).
                    tensor = _quantize_and_place(
                        model, tensor, spec, mesh, dtype
                    )
                elif mesh is not None:
                    tensor = jax.device_put(
                        tensor, NamedSharding(mesh, spec)
                    )
                _set_path(params, path, tensor)
                n += 1
    if hasattr(model, "finalize_params"):
        params = model.finalize_params(params, mesh)
    logger.info(
        "loaded %d tensors from %d shard(s) in %.1fs",
        n,
        len(files),
        time.monotonic() - start,
    )
    return params


def get_model(
    model_config,
    *,
    load_format: str = "auto",
    mesh: Mesh | None = None,
    rng: jax.Array | None = None,
) -> tuple[Any, dict]:
    """Build (model, params).  load_format: "auto" reads safetensors,
    "dummy" random-initializes (tests, perf smoke)."""
    cls = get_model_class(model_config.architecture)
    model = cls(model_config)
    # Model-specific mesh preconditions (e.g. EP expert divisibility),
    # checked before any device placement so failures are clear errors
    # rather than GSPMD sharding failures mid-load.
    if mesh is not None and hasattr(model, "validate_mesh"):
        model.validate_mesh(mesh)
    if load_format == "dummy":
        rng = rng if rng is not None else jax.random.PRNGKey(model_config.seed)
        # One jitted program for the whole tree: init_params issues ~1
        # tiny RNG/cast op per tensor, and on a remote-compile runtime
        # every unique small program costs ~1 s of compile round trip
        # (measured: 142 s to dummy-init a 1B model op-by-op).
        params = jax.jit(model.init_params)(rng)
        if mesh is not None or getattr(model, "quant_method", None):
            specs = (
                model.partition_specs()
                if hasattr(model, "partition_specs")
                else None
            )
            params = _place_tree(model, params, specs, mesh)
        return model, params
    model_dir = resolve_model_dir(model_config.model)
    params = load_hf_weights(model, model_dir, mesh=mesh)
    return model, params
