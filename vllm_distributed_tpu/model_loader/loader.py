"""Model construction + HF safetensors → sharded jax arrays.

The TPU analog of the reference's `collective_rpc("load_model")` step
(launch.py:292, SURVEY.md §5.4): weights come from a local HF snapshot
(safetensors shards), are read tensor-by-tensor on host, mapped through
the model's ``map_hf_name`` table, and placed onto the device mesh with
the model's ``partition_specs`` — each host materializes only its own
shard bytes when a mesh is given (host-parallel load).
"""

from __future__ import annotations

import glob
import os
import time
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.models import get_model_class

logger = init_logger(__name__)


def resolve_model_dir(model: str) -> str:
    """Local dir, or an HF-hub snapshot already present in the cache."""
    if os.path.isdir(model):
        return model
    cache = os.environ.get(
        "HF_HUB_CACHE",
        os.path.join(
            os.environ.get(
                "HF_HOME", os.path.expanduser("~/.cache/huggingface")
            ),
            "hub",
        ),
    )
    repo_dir = os.path.join(cache, "models--" + model.replace("/", "--"))
    snapshots = sorted(glob.glob(os.path.join(repo_dir, "snapshots", "*")))
    if snapshots:
        return snapshots[-1]
    raise FileNotFoundError(
        f"model {model!r} is neither a local directory nor a cached HF "
        f"snapshot (no network egress; pre-download weights)"
    )


def _set_path(tree: dict, path: tuple, value) -> None:
    node = tree
    for key in path[:-1]:
        if isinstance(key, int):
            node = node[key]
        else:
            node = node.setdefault(key, {})
    node[path[-1]] = value


def _sharding_for(path: tuple, specs: dict | None, mesh: Mesh | None):
    if mesh is None:
        return None
    spec = P()
    if specs is not None:
        node: Any = specs
        try:
            for key in path:
                node = node[key]
            spec = node
        except (KeyError, IndexError, TypeError):
            spec = P()
    return NamedSharding(mesh, spec)


def load_hf_weights(
    model,
    model_dir: str,
    *,
    mesh: Mesh | None = None,
    dtype: Any = None,
) -> dict:
    """Stream every tensor of every *.safetensors shard into the param
    tree.  Layer-norm/bias params keep float32 precision headroom is not
    needed — everything is cast to the model dtype."""
    from safetensors import safe_open

    dtype = jnp.dtype(dtype or model.dtype)
    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {model_dir}")
    # load_specs (when present) describes per-tensor placement DURING the
    # load, which can differ from the final partition_specs — e.g. MoE
    # expert tensors arrive unstacked and are stacked by finalize_params.
    if hasattr(model, "load_specs"):
        specs = model.load_specs()
    elif hasattr(model, "partition_specs"):
        specs = model.partition_specs()
    else:
        specs = None

    params: dict = {"layers": [{} for _ in range(model.num_layers)]}
    start = time.monotonic()
    n = 0
    cpu = jax.devices("cpu")[0]
    for file in files:
        with safe_open(file, framework="flax") as f:
            for name in f.keys():
                mapped = model.map_hf_name(name)
                if mapped is None:
                    continue
                path, transform = mapped
                with jax.default_device(cpu):
                    tensor = f.get_tensor(name)
                    if transform == "T":
                        tensor = tensor.T
                    tensor = tensor.astype(dtype)
                sharding = _sharding_for(path, specs, mesh)
                if sharding is not None:
                    tensor = jax.device_put(tensor, sharding)
                _set_path(params, path, tensor)
                n += 1
    if hasattr(model, "finalize_params"):
        params = model.finalize_params(params, mesh)
    logger.info(
        "loaded %d tensors from %d shard(s) in %.1fs",
        n,
        len(files),
        time.monotonic() - start,
    )
    return params


def get_model(
    model_config,
    *,
    load_format: str = "auto",
    mesh: Mesh | None = None,
    rng: jax.Array | None = None,
) -> tuple[Any, dict]:
    """Build (model, params).  load_format: "auto" reads safetensors,
    "dummy" random-initializes (tests, perf smoke)."""
    cls = get_model_class(model_config.architecture)
    model = cls(model_config)
    # Model-specific mesh preconditions (e.g. EP expert divisibility),
    # checked before any device placement so failures are clear errors
    # rather than GSPMD sharding failures mid-load.
    if mesh is not None and hasattr(model, "validate_mesh"):
        model.validate_mesh(mesh)
    if load_format == "dummy":
        rng = rng if rng is not None else jax.random.PRNGKey(model_config.seed)
        params = model.init_params(rng)
        if mesh is not None:
            specs = model.partition_specs()
            # tree.map flattens `specs` up to the structure of `params`, so
            # each PartitionSpec (a tuple subclass) arrives whole as `s`.
            params = jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                params,
                specs,
            )
        return model, params
    model_dir = resolve_model_dir(model_config.model)
    params = load_hf_weights(model, model_dir, mesh=mesh)
    return model, params
