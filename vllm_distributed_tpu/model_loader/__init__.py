from vllm_distributed_tpu.model_loader.loader import get_model, load_hf_weights

__all__ = ["get_model", "load_hf_weights"]
