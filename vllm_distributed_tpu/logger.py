"""Structured logging with host/rank prefixes.

The reference relies on vLLM's init_logger (launch.py:40,54) and forwards
remote worker tracebacks to the driver log (launch.py:531-535). We provide
an equivalent: every process tags records with its hostname and, once the
distributed runtime is initialized, its process index.
"""

from __future__ import annotations

import logging
import os
import socket
import sys

_FORMAT = (
    "%(levelname)s %(asctime)s [%(hostprefix)s] %(name)s:%(lineno)d  %(message)s"
)
_DATEFMT = "%m-%d %H:%M:%S"

_process_tag: str | None = None


def set_process_tag(tag: str) -> None:
    """Set a tag (e.g. "worker-3" or "agent") included in every log record."""
    global _process_tag
    _process_tag = tag


class _HostPrefixFilter(logging.Filter):
    def __init__(self) -> None:
        super().__init__()
        self._host = socket.gethostname()

    def filter(self, record: logging.LogRecord) -> bool:
        tag = _process_tag or f"pid{os.getpid()}"
        record.hostprefix = f"{self._host}/{tag}"
        return True


_configured = False


def _configure_root() -> None:
    global _configured
    if _configured:
        return
    _configured = True
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT, datefmt=_DATEFMT))
    handler.addFilter(_HostPrefixFilter())
    from vllm_distributed_tpu import envs

    root = logging.getLogger("vllm_distributed_tpu")
    root.setLevel(envs.VDT_LOG_LEVEL.upper())
    root.addHandler(handler)
    root.propagate = False


def init_logger(name: str) -> logging.Logger:
    _configure_root()
    return logging.getLogger(name)
