"""vllm-distributed-tpu: a TPU-native distributed LLM serving framework.

A from-scratch reimplementation of the capability surface of
koush/vllm-distributed (multi-node launcher + the vLLM engine it drives),
designed TPU-first: JAX/XLA for the compute path, Pallas kernels for paged
attention, pjit/NamedSharding over a device mesh for TP/DP/EP, XLA
collectives over ICI/DCN for the data plane, and an asyncio RPC control
plane over the host network (reference: /root/reference/src/launch.py,
rpc.py, rpc_reader.py).
"""

from vllm_distributed_tpu.version import __version__

__all__ = ["__version__"]
