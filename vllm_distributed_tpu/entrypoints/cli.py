"""CLI — `vdt` / `python -m vllm_distributed_tpu`.

The rebuild of the reference's launcher surface (launch.py:668-679 +
the vLLM CLI families it mounts, launch.py:21-25, 465-507; SURVEY.md §2
C7): ``serve`` boots the engine + OpenAI server, ``remote <server_ip>``
turns this host into a worker agent, plus ``bench``, ``collect-env``,
``run-batch``, and client-side ``chat``/``complete``.  ``${VAR}`` tokens
in argv are env-expanded (FlexibleArgumentParser parity).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.version import __version__

logger = init_logger(__name__)


def _expand_env(argv: list[str]) -> list[str]:
    return [os.path.expandvars(a) for a in argv]


def _add_server_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--host", type=str, default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--ssl-certfile", type=str, default=None)
    parser.add_argument("--ssl-keyfile", type=str, default=None)
    parser.add_argument("--served-model-name", type=str, default=None)
    parser.add_argument("--chat-template", type=str, default=None)
    parser.add_argument("--tool-call-parser", type=str, default=None)
    parser.add_argument("--tool-parser-plugin", type=str, default=None)
    parser.add_argument(
        "--enable-auto-tool-choice", action="store_true", default=False
    )
    parser.add_argument("--disable-log-requests", action="store_true")
    parser.add_argument(
        "--api-key",
        type=str,
        default=None,
        help="require 'Authorization: Bearer <key>' on API endpoints",
    )
    parser.add_argument(
        "--log-config",
        type=str,
        default=None,
        help="JSON logging-config file applied via logging.config."
        "dictConfig (the reference's load_log_config, launch.py:34,423)",
    )


def make_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="vdt",
        description="TPU-native distributed LLM serving",
    )
    parser.add_argument("--version", action="version", version=__version__)
    sub = parser.add_subparsers(dest="command", required=True)

    serve = sub.add_parser("serve", help="start the OpenAI API server")
    serve.add_argument("model_tag", type=str, nargs="?", default=None)
    _add_server_args(serve)
    EngineArgs.add_cli_args(serve)

    remote = sub.add_parser(
        "remote", help="offer this host's chips to a server"
    )
    remote.add_argument("server_ip", type=str)
    remote.add_argument("--server-port", type=int, default=None)

    router = sub.add_parser(
        "router",
        help="multi-replica front-end: cache-affinity placement + live "
        "request migration over N `vdt serve` replicas",
    )
    router.add_argument("--host", type=str, default="0.0.0.0")
    router.add_argument("--port", type=int, default=8080)
    router.add_argument(
        "--api-key",
        type=str,
        default=None,
        help="require 'Authorization: Bearer <key>' on API endpoints "
        "(forwarded verbatim to replicas)",
    )
    from vllm_distributed_tpu.config import RouterArgs

    RouterArgs.add_cli_args(router)

    bench = sub.add_parser(
        "bench",
        help="latency/throughput bench (offline) or serve (live HTTP)",
    )
    bench.add_argument(
        "mode", choices=["latency", "throughput", "serve"],
        default="throughput", nargs="?",
    )
    bench.add_argument("--input-len", type=int, default=32)
    bench.add_argument("--output-len", type=int, default=64)
    bench.add_argument("--num-prompts", type=int, default=32)
    # serve mode: drives a LIVE server over HTTP/SSE (the reference's
    # `vllm bench serve`, launch.py:21-25) — engine args unused.
    bench.add_argument("--url", default="http://localhost:8000")
    bench.add_argument("--concurrency", type=int, default=8)
    bench.add_argument(
        "--request-rate",
        type=float,
        default=None,
        help="serve mode: OPEN-LOOP Poisson arrivals at this rate "
        "(req/s) instead of closed-loop concurrency — set it above "
        "capacity to measure overload shedding; rejected (429) and "
        "timed-out requests are accounted separately and never "
        "pollute the latency percentiles",
    )
    bench.add_argument(
        "--ramp",
        type=str,
        default=None,
        metavar="R1:S1,R2:S2,...",
        help="serve mode: piecewise OPEN-LOOP Poisson arrival schedule "
        "— run at R1 req/s for S1 seconds, then R2 for S2, ... "
        "(sweep the rate up and down to exercise an autoscaled "
        "fleet).  Client p50/p99 and rejected/timed-out counts are "
        "reported per segment; --num-prompts is ignored",
    )
    bench.add_argument(
        "--deadline-ms",
        type=int,
        default=None,
        help="serve mode: per-request deadline sent with every request",
    )
    bench.add_argument(
        "--slo-class",
        dest="slo_classes",
        action="append",
        default=None,
        metavar="NAME[:WEIGHT]",
        help="serve mode: send requests under this SLO class "
        "(repeatable; an integer weight sets the mix, e.g. "
        "--slo-class interactive:3 --slo-class batch:1).  Client "
        "TTFT/ITL p50/p99 are reported per class, plus per-class "
        "goodput deltas scraped from the server's "
        "vllm:goodput_requests_total counters — so scheduler changes "
        "are judged on SLO attainment, not just tokens/s",
    )
    # --seed comes from EngineArgs.add_cli_args below (shared with the
    # engine modes); serve mode reuses it for the open-loop arrival
    # processes and tenant length distributions, and records it in the
    # results JSON so multi-tenant A/B runs are reproducible.
    bench.add_argument(
        "--tenant",
        dest="tenants",
        action="append",
        default=None,
        metavar="NAME:key=val,...",
        help="serve mode: MULTI-TENANT load — repeatable named traffic "
        'profiles, e.g. --tenant "chat:class=interactive,arrival='
        'bursty,rate=8,burst=4,input=16-64,output=32-128" --tenant '
        '"bulk:class=batch,arrival=closed,concurrency=16".  Keys: '
        "class (SLO class sent with every request, default NAME), "
        "arrival (poisson|bursty|closed), rate (req/s for the "
        "open-loop arrivals), burst (arrivals per burst epoch), "
        "concurrency (closed-loop streams), input/output (token "
        "lengths, INT or LO-HI sampled uniformly per request).  All "
        "tenants run concurrently for --tenant-seconds; the report "
        "carries per-tenant client percentiles and shed counts plus "
        "per-class server goodput deltas — the instrument every QoS "
        "scheduling change is judged with",
    )
    bench.add_argument(
        "--tenant-seconds",
        type=float,
        default=10.0,
        help="multi-tenant mode: wall-clock duration of the run",
    )
    bench.add_argument(
        "--disagg",
        action="store_true",
        default=False,
        help="serve mode: run the prefill/decode INTERFERENCE scenario "
        "instead of the uniform load — a steady batch of decode "
        "streams with one long-prompt stream injected mid-run, "
        "reporting the decode ITL p99 before vs during the long "
        "prefill and the long prompt's TTFT.  Run it once against a "
        "mixed-pool router and once against a role-separated one "
        "(--fleet-prefill/--fleet-decode or VDT_ROUTER_ROLE "
        "replicas): role separation should hold the decode p99 flat "
        "(the ISSUE 15 A/B)",
    )
    bench.add_argument(
        "--disagg-prompt-len",
        type=int,
        default=1024,
        help="interference scenario: long-prompt length in tokens",
    )
    bench.add_argument(
        "--disagg-decode-streams",
        type=int,
        default=4,
        help="interference scenario: steady decode streams",
    )
    bench.add_argument(
        "--shared-prefix-len",
        type=int,
        default=0,
        help="serve mode: prepend this many SHARED prompt tokens to "
        "every request (router affinity A/B workload: with "
        "--enable-prefix-caching replicas, affinity routing should "
        "show a higher vllm:prefix_cache_hits rate than round_robin)",
    )
    EngineArgs.add_cli_args(bench)

    sub.add_parser("collect-env", help="print environment diagnostics")

    run_batch = sub.add_parser(
        "run-batch", help="run a JSONL batch file offline"
    )
    run_batch.add_argument("-i", "--input-file", required=True)
    run_batch.add_argument("-o", "--output-file", required=True)
    EngineArgs.add_cli_args(run_batch)

    for name in ("chat", "complete"):
        client = sub.add_parser(name, help=f"{name} against a server")
        client.add_argument("--url", default="http://localhost:8000")
        client.add_argument("--model", default=None)
        client.add_argument("prompt", nargs="?", default=None)

    return parser


# ---- serve ----
async def _serve_async(args: argparse.Namespace) -> None:
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        build_app,
        init_app_state,
        serve_http,
    )
    from vllm_distributed_tpu.entrypoints.openai.tool_parsers import (
        ToolParserManager,
    )

    if args.model_tag:
        args.model = args.model_tag
    if getattr(args, "log_config", None):
        import logging.config

        # vdt-lint: disable=async-blocking — one-shot startup read
        # before the loop serves any traffic.
        with open(args.log_config) as f:
            logging.config.dictConfig(json.load(f))
    if args.tool_parser_plugin:
        ToolParserManager.import_tool_parser(args.tool_parser_plugin)
    engine_args = EngineArgs.from_cli_args(args)
    if engine_args.num_hosts > 1:
        engine_args.distributed_executor_backend = "multihost"
    loop = asyncio.get_running_loop()
    engine = await loop.run_in_executor(
        None, lambda: AsyncLLM.from_engine_args(engine_args)
    )
    chat_template = None
    if args.chat_template:
        if os.path.exists(args.chat_template):
            # vdt-lint: disable=async-blocking — one-shot startup read
            # before the loop serves any traffic.
            with open(args.chat_template) as f:
                chat_template = f.read()
        else:
            chat_template = args.chat_template
    from vllm_distributed_tpu import envs

    state = init_app_state(
        engine,
        served_model_name=args.served_model_name,
        tool_call_parser=args.tool_call_parser,
        enable_auto_tool_choice=args.enable_auto_tool_choice,
        chat_template=chat_template,
        api_key=args.api_key,
        # Stable replica identity (ISSUE 10 satellite): operator-pinned
        # via VDT_REPLICA_ID, else this server's host:port.
        replica_id=envs.VDT_REPLICA_ID or f"{args.host}:{args.port}",
    )
    app = build_app(state)
    runner = await serve_http(
        app,
        host=args.host,
        port=args.port,
        ssl_certfile=args.ssl_certfile,
        ssl_keyfile=args.ssl_keyfile,
    )
    # Graceful drain on SIGTERM (ISSUE 8): stop admission (429 + drain
    # state in /health), let in-flight requests finish under the drain
    # deadline, journal the rest to VDT_DRAIN_JOURNAL_PATH for the
    # restarted process to replay, THEN exit.  A second SIGTERM (or
    # SIGINT) skips the wait.
    stop = asyncio.Event()
    sigterm_seen = False

    def _on_sigterm() -> None:
        nonlocal sigterm_seen
        if stop.is_set():
            return
        if sigterm_seen:
            stop.set()  # second signal: exit now
            return
        sigterm_seen = True

        async def _drain_and_stop() -> None:
            try:
                if state.engine.draining:
                    # An HTTP-initiated /drain is already in progress
                    # (or finished): wait it out instead of re-draining
                    # — stopping now would cancel its journal write.
                    while state.engine.drain_state_name == "draining":
                        await asyncio.sleep(0.1)
                else:
                    await state.engine.drain()
            except Exception:  # noqa: BLE001 — drain is best-effort
                logger.exception("drain on SIGTERM failed")
            finally:
                stop.set()

        logger.warning("SIGTERM: draining before shutdown")
        asyncio.get_running_loop().create_task(_drain_and_stop())

    import signal

    try:
        loop.add_signal_handler(signal.SIGTERM, _on_sigterm)
    except (NotImplementedError, RuntimeError):
        pass  # non-unix platforms / nested loops: plain kill semantics
    try:
        await stop.wait()  # serve until drained + stopped (or killed)
    finally:
        await runner.cleanup()
        engine.shutdown()


def cmd_serve(args: argparse.Namespace) -> None:
    asyncio.run(_serve_async(args))


# ---- remote ----
def cmd_remote(args: argparse.Namespace) -> None:
    from vllm_distributed_tpu.distributed.agent import remote_main

    remote_main(args.server_ip, args.server_port)


# ---- router ----
async def _router_async(args: argparse.Namespace) -> None:
    from vllm_distributed_tpu.config import RouterArgs
    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        serve_http,
    )
    from vllm_distributed_tpu.router.app import (
        RouterState,
        build_router_app,
    )
    from vllm_distributed_tpu.tracing import configure_from_env

    configure_from_env(host="router")
    router_args = RouterArgs.from_cli_args(args)
    urls = router_args.resolved_replicas()
    from vllm_distributed_tpu import envs

    fleet_on = (
        router_args.fleet_size > 0
        or router_args.fleet_prefill > 0
        or router_args.fleet_decode > 0
        or router_args.autoscale
    )
    if not urls and not fleet_on:
        raise SystemExit(
            "router needs replicas: pass --replica URL (repeatable), "
            "set VDT_ROUTER_REPLICAS, or enable the managed fleet "
            "(--fleet-size/--autoscale with --fleet-cmd)"
        )
    state = RouterState(
        urls,
        policy=router_args.policy,
        max_migrations=router_args.max_migrations,
        affinity_block_tokens=router_args.affinity_block_tokens,
        affinity_capacity=router_args.affinity_capacity,
        affinity_min_tokens=router_args.affinity_min_tokens,
        health_interval=router_args.health_interval,
        connect_timeout=router_args.connect_timeout,
        read_timeout=router_args.read_timeout,
        api_key=args.api_key,
        allow_empty_pool=fleet_on,
    )
    # Crash-safe state (ISSUE 17; default off): open the WAL and
    # recover whatever the previous incarnation left — fleet membership
    # to re-adopt, in-flight journals to replay on reconnect.
    persist_log = None
    recovered = None
    state_dir = router_args.resolved_state_dir()
    if state_dir:
        from vllm_distributed_tpu.router.persist import RouterStateLog

        persist_log = RouterStateLog(state_dir)
        recovered = persist_log.open()
        state.attach_persist(persist_log, recovered)
        logger.info(
            "router durable state at %s: recovered %d replica "
            "record(s), %d in-flight journal(s)",
            state_dir,
            len(recovered.replicas),
            len(recovered.journals),
        )
    if fleet_on:
        # Elastic fleet (ISSUE 13): the router owns `vdt serve`
        # replicas as supervised children, optionally resized by the
        # autoscaler control loop.
        from vllm_distributed_tpu.router.app import _fleet_slo
        from vllm_distributed_tpu.router.fleet import (
            Autoscaler,
            AutoscalerConfig,
            CommandLauncher,
            ReplicaManager,
        )

        template = router_args.fleet_cmd or envs.VDT_FLEET_CMD
        if not template:
            raise SystemExit(
                "fleet mode needs a replica command template: pass "
                "--fleet-cmd 'vdt serve ... --port {port}' or set "
                "VDT_FLEET_CMD"
            )
        autoscaler = None
        cfg = None
        if router_args.autoscale:
            cfg = AutoscalerConfig.from_env()
            if router_args.autoscale_min is not None:
                cfg.min_replicas = router_args.autoscale_min
            if router_args.autoscale_max is not None:
                cfg.max_replicas = router_args.autoscale_max
        target = router_args.fleet_size or (
            cfg.min_replicas if cfg is not None else 0
        )
        manager = ReplicaManager(
            state.pool,
            state.metrics,
            CommandLauncher(template),
            target=target,
            # Disaggregated pools (ISSUE 15): fixed per-role counts
            # spawned from the same template with VDT_ROUTER_ROLE set.
            role_targets={
                "prefill": router_args.fleet_prefill,
                "decode": router_args.fleet_decode,
            },
            # Durable membership (ISSUE 17): spawn/retire events land in
            # the WAL so the next incarnation can re-adopt live children.
            persist=persist_log,
        )
        # Recovered scale targets win over the CLI defaults: a crash
        # between a scale-up and its convergence must not revert the
        # fleet (the first reconcile tick would retire the extras the
        # previous incarnation just spawned).
        if recovered is not None and recovered.fleet_target is not None:
            if recovered.fleet_target != manager.target:
                logger.info(
                    "restoring recovered fleet target %d "
                    "(CLI default was %d)",
                    recovered.fleet_target,
                    manager.target,
                )
            manager.target = recovered.fleet_target
            for role, n in (recovered.fleet_role_targets or {}).items():
                if role in manager.role_targets:
                    manager.role_targets[role] = int(n)
        manager.persist_targets()
        if cfg is not None:

            async def _slo_classes() -> dict:
                return (await _fleet_slo(state)).get("classes", {})

            autoscaler = Autoscaler(
                manager,
                state.pool,
                state.metrics,
                cfg,
                slo_probe=_slo_classes,
                # Long-prompt arrival EWMA observed by the proxy path;
                # drives the per-role prefill-pool target (ISSUE 16).
                prefill_demand=state.prefill_demand,
            )
        state.attach_fleet(manager, autoscaler)
    app = build_router_app(state)
    runner = await serve_http(app, host=args.host, port=args.port)
    if fleet_on:
        logger.info(
            "router managing a fleet of %d replica(s)%s (template: %s)",
            state.manager.target,
            " with autoscaling" if state.autoscaler is not None else "",
            router_args.fleet_cmd or envs.VDT_FLEET_CMD,
        )
    if urls:
        logger.info(
            "router fronting %d replica(s) with policy=%s: %s",
            len(urls),
            state.policy,
            ", ".join(urls),
        )
    stop = asyncio.Event()
    sigterm_seen = False

    def _on_sigterm() -> None:
        # Graceful fleet drain on SIGTERM (ISSUE 13 satellite, parity
        # with the replica-side SIGTERM drain from ISSUE 8): drain
        # every MANAGED replica (bounded by
        # VDT_FLEET_DRAIN_TIMEOUT_SECONDS) and reap every child before
        # exit, so a router kill never leaks `vdt serve` processes.  A
        # second SIGTERM (or SIGINT) skips the wait; children are still
        # reaped by the runner cleanup below.
        nonlocal sigterm_seen
        if stop.is_set():
            return
        if sigterm_seen or state.manager is None:
            stop.set()
            return
        sigterm_seen = True

        async def _drain_and_stop() -> None:
            try:
                await state.manager.stop(drain=True)
            except Exception:  # noqa: BLE001 — drain is best-effort; cleanup still reaps
                logger.exception("fleet drain on SIGTERM failed")
            finally:
                stop.set()

        logger.warning("SIGTERM: draining managed fleet before shutdown")
        asyncio.get_running_loop().create_task(_drain_and_stop())

    def _on_sigint() -> None:
        stop.set()

    import signal

    loop = asyncio.get_running_loop()
    for sig, handler in (
        (signal.SIGTERM, _on_sigterm),
        (signal.SIGINT, _on_sigint),
    ):
        try:
            loop.add_signal_handler(sig, handler)
        except (NotImplementedError, RuntimeError):
            pass
    try:
        await stop.wait()
    finally:
        # _on_cleanup stops the autoscaler and the manager (idempotent
        # if SIGTERM already drained) — all children reaped either way.
        await runner.cleanup()


def cmd_router(args: argparse.Namespace) -> None:
    asyncio.run(_router_async(args))


# ---- bench ----
def parse_ramp(spec: str) -> list[tuple[float, float]]:
    """Parse a piecewise arrival schedule: ``"r1:s1,r2:s2,..."`` →
    ``[(rate_rps, seconds), ...]``.  A zero rate is an idle dwell
    (useful as the settle tail of an autoscale-down assertion).  Shared
    by bench-serve ``--ramp`` and the chaos ramp harness
    (tools/chaos_soak.py)."""
    segments: list[tuple[float, float]] = []
    for piece in spec.split(","):
        piece = piece.strip()
        if not piece:
            continue
        rate_s, sep, dur_s = piece.partition(":")
        try:
            if not sep:
                raise ValueError
            rate, dur = float(rate_s), float(dur_s)
            if rate < 0 or dur <= 0:
                raise ValueError
        except ValueError:
            raise SystemExit(
                f"bad --ramp segment {piece!r}: want RATE:SECONDS with "
                "RATE >= 0 and SECONDS > 0"
            )
        segments.append((rate, dur))
    if not segments:
        raise SystemExit("--ramp needs at least one RATE:SECONDS segment")
    return segments


TENANT_ARRIVALS = ("poisson", "bursty", "closed")


def parse_len_range(spec: str, what: str) -> tuple[int, int]:
    """``"8"`` → (8, 8); ``"32-128"`` → (32, 128) (uniform bounds)."""
    lo_s, sep, hi_s = spec.partition("-")
    try:
        lo = int(lo_s)
        hi = int(hi_s) if sep else lo
        if lo <= 0 or hi < lo:
            raise ValueError
    except ValueError:
        raise SystemExit(
            f"bad tenant {what} length {spec!r}: want INT or LO-HI "
            "with 0 < LO <= HI"
        )
    return lo, hi


def parse_tenants(specs: list[str]) -> list[dict]:
    """Parse repeatable ``--tenant "NAME:key=val,..."`` profiles
    (ISSUE 16's multi-tenant load generator).

    Each profile is an independent traffic source with its own SLO
    class, arrival process, and prompt/output length distributions —
    e.g. interactive chat (bursty short prompts), long-context
    summarization (Poisson long prompts), bulk batch (closed-loop).
    """
    tenants: list[dict] = []
    seen: set[str] = set()
    for spec in specs:
        name, sep, rest = spec.partition(":")
        name = name.strip()
        if not name or not sep:
            raise SystemExit(
                f"bad --tenant {spec!r}: want NAME:key=val,..."
            )
        if name in seen:
            raise SystemExit(f"duplicate --tenant name {name!r}")
        seen.add(name)
        profile = {
            "name": name,
            "slo_class": name,
            "arrival": "poisson",
            "rate": 4.0,
            "burst": 4,
            "concurrency": 4,
            "input": (32, 32),
            "output": (64, 64),
        }
        for kv in rest.split(","):
            kv = kv.strip()
            if not kv:
                continue
            key, sep2, val = kv.partition("=")
            key, val = key.strip(), val.strip()
            if not sep2 or not val:
                raise SystemExit(
                    f"bad --tenant {name!r} entry {kv!r}: want key=val"
                )
            try:
                if key == "class":
                    profile["slo_class"] = val
                elif key == "arrival":
                    if val not in TENANT_ARRIVALS:
                        raise ValueError
                    profile["arrival"] = val
                elif key == "rate":
                    profile["rate"] = float(val)
                    if profile["rate"] <= 0:
                        raise ValueError
                elif key == "burst":
                    profile["burst"] = int(val)
                    if profile["burst"] < 1:
                        raise ValueError
                elif key == "concurrency":
                    profile["concurrency"] = int(val)
                    if profile["concurrency"] < 1:
                        raise ValueError
                elif key in ("input", "output"):
                    profile[key] = parse_len_range(val, key)
                else:
                    raise SystemExit(
                        f"unknown --tenant key {key!r} (want class/"
                        "arrival/rate/burst/concurrency/input/output)"
                    )
            except ValueError:
                raise SystemExit(
                    f"bad --tenant {name!r} value for {key!r}: {val!r}"
                )
        tenants.append(profile)
    return tenants


def _percentiles(xs: list[float]) -> dict:
    xs = sorted(xs)

    def pct(p):
        return round(xs[min(int(len(xs) * p), len(xs) - 1)], 4)

    return {"p50": pct(0.5), "p90": pct(0.9), "p99": pct(0.99)}


async def _bench_disagg_interference(args: argparse.Namespace) -> dict:
    """The ISSUE 15 interference scenario: steady decode streams with
    one long-prompt stream injected once they are warm.  Reports the
    decode streams' client ITL p99 split into before-vs-during the long
    prefill, plus the long prompt's TTFT — the numbers that judge
    mixed vs role-separated pools.  The deployment under test is
    whatever --url fronts; the A/B is two runs against two routers."""
    import aiohttp

    url = args.url.rstrip("/")
    long_len = args.disagg_prompt_len
    n_decode = args.disagg_decode_streams
    arrivals: list[list[float]] = [[] for _ in range(n_decode)]
    long_marks: dict[str, float] = {}
    errors = {"decode": 0, "long": 0}

    async def stream(session, body, on_chunk) -> None:
        async with session.post(
            f"{url}/v1/completions", json=body
        ) as resp:
            resp.raise_for_status()
            async for raw in resp.content:
                line = raw.decode().strip()
                if not line.startswith("data:"):
                    continue
                payload = line[5:].strip()
                if payload == "[DONE]":
                    break
                chunk = json.loads(payload)
                if chunk.get("choices"):
                    on_chunk()

    async def decode_stream(session, i: int) -> None:
        body = {
            "model": args.model or "bench",
            "prompt": [(13 * i + j) % 900 + 1 for j in range(args.input_len)],
            "max_tokens": args.output_len,
            "temperature": 0.0,
            "ignore_eos": True,
            "stream": True,
        }
        try:
            await stream(
                session, body,
                lambda: arrivals[i].append(time.perf_counter()),
            )
        except Exception:  # noqa: BLE001 — bench client: count, move on
            errors["decode"] += 1

    async def long_stream(session) -> None:
        body = {
            "model": args.model or "bench",
            "prompt": [(17 + j) % 900 + 1 for j in range(long_len)],
            "max_tokens": 8,
            "temperature": 0.0,
            "ignore_eos": True,
            "stream": True,
        }

        def first() -> None:
            long_marks.setdefault("first", time.perf_counter())

        long_marks["start"] = time.perf_counter()
        try:
            await stream(session, body, first)
        except Exception:  # noqa: BLE001 — bench client: count, move on
            errors["long"] += 1
        long_marks["end"] = time.perf_counter()

    timeout = aiohttp.ClientTimeout(total=None, sock_read=600)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        decode_tasks = [
            asyncio.create_task(decode_stream(session, i))
            for i in range(n_decode)
        ]
        # Warm: every decode stream steadily producing before the long
        # prompt lands (bounded wait; slow deployments just inject).
        deadline = time.perf_counter() + 30
        while time.perf_counter() < deadline:
            if all(len(a) >= 4 for a in arrivals):
                break
            await asyncio.sleep(0.02)
        await long_stream(session)
        await asyncio.gather(*decode_tasks)

    start = long_marks.get("start", 0.0)
    first = long_marks.get("first")
    end = long_marks.get("end", start)
    window_end = first if first is not None else end
    before: list[float] = []
    during: list[float] = []
    for a in arrivals:
        for prev, cur in zip(a, a[1:]):
            itl = cur - prev
            if cur <= start:
                before.append(itl)
            elif prev >= start and cur <= window_end:
                during.append(itl)
    if len(during) < 3:
        # The prefill window was too short to straddle samples (the
        # role-separated happy case): widen to the whole long stream.
        during = [
            cur - prev
            for a in arrivals
            for prev, cur in zip(a, a[1:])
            if prev >= start and cur <= end
        ] or during
    return {
        "mode": "serve",
        "scenario": "disagg_interference",
        "url": url,
        "decode_streams": n_decode,
        "long_prompt_len": long_len,
        "long_ttft_s": (
            round(first - start, 4) if first is not None else None
        ),
        "decode_itl_ms": {
            "before": (
                {
                    k: round(v * 1e3, 3)
                    for k, v in _percentiles(before).items()
                }
                if before
                else None
            ),
            "during_long_prefill": (
                {
                    k: round(v * 1e3, 3)
                    for k, v in _percentiles(during).items()
                }
                if during
                else None
            ),
        },
        "errors": dict(errors),
    }


async def _bench_serve_async(args: argparse.Namespace) -> dict:
    """Drive a LIVE server with concurrent streaming completions and
    measure TTFT/ITL/throughput as the CLIENT sees them over SSE, then
    cross-check against the server's own /metrics histograms (the
    serving metrics BASELINE.md tracks are HTTP-path numbers, not
    engine-loop numbers)."""
    import aiohttp

    if getattr(args, "disagg", False):
        return await _bench_disagg_interference(args)

    url = args.url.rstrip("/")
    # The closed-loop semaphore is unused by the tenant path (each
    # profile carries its own concurrency), so tenant-only invocations
    # may omit --concurrency entirely.
    sem = asyncio.Semaphore(getattr(args, "concurrency", None) or 1)
    ttfts: list[float] = []
    itls: list[float] = []
    out_tokens = 0
    # Overload accounting (ISSUE 8): sheds are OUTCOMES, not latency
    # samples — a 429'd or timed-out request must never pollute the
    # percentiles of the requests the server actually served.
    request_rate = getattr(args, "request_rate", None)
    # "unavailable" = 503s (breaker rejections / exhausted retry
    # budget with no live target) — a distinct outcome from generic
    # transport errors so a resilience A/B can read them apart.
    counts = {
        "completed": 0,
        "rejected": 0,
        "timed_out": 0,
        "unavailable": 0,
        "errors": 0,
    }

    # Piecewise rate sweep (ISSUE 13): open-loop segments with
    # per-segment accounting, the workload an autoscaler acceptance run
    # (and the chaos ramp harness) is judged against.
    ramp = getattr(args, "ramp", None)
    ramp_segments = parse_ramp(ramp) if ramp else None
    if ramp_segments and request_rate is not None:
        raise SystemExit("--ramp and --request-rate are mutually exclusive")

    # Reproducible stochastic load (ISSUE 16 satellite): ONE seed
    # drives every arrival process and length distribution, and is
    # recorded in the results JSON, so two A/B runs offer the same
    # workload down to the per-request token counts.
    import random

    seed = int(getattr(args, "seed", None) or 12345)

    # Multi-tenant profiles (ISSUE 16): independent concurrent traffic
    # sources, each with its own class/arrivals/length distributions.
    tenant_specs = getattr(args, "tenants", None)
    tenants = parse_tenants(tenant_specs) if tenant_specs else None
    if tenants and (request_rate is not None or ramp_segments):
        raise SystemExit(
            "--tenant is mutually exclusive with --request-rate/--ramp"
        )
    tenant_seconds = float(getattr(args, "tenant_seconds", None) or 10.0)
    tenant_runs: list[dict] = [
        {
            "profile": p,
            # Per-tenant NAMED streams: adding or reordering a tenant
            # can't shift another tenant's arrival or length draws.
            "arr_rng": random.Random(f"{seed}:{p['name']}:arrival"),
            "len_rng": random.Random(f"{seed}:{p['name']}:length"),
            "offered": 0,
            "completed": 0,
            "rejected": 0,
            "timed_out": 0,
            "unavailable": 0,
            "errors": 0,
            "ttfts": [],
            "itls": [],
        }
        for p in (tenants or ())
    ]
    seg_stats: list[dict] = [
        {
            "rate_rps": rate,
            "seconds": dur,
            "offered": 0,
            "completed": 0,
            "rejected": 0,
            "timed_out": 0,
            "unavailable": 0,
            "errors": 0,
            "ttfts": [],
            "itls": [],
        }
        for rate, dur in (ramp_segments or ())
    ]

    # Per-class request mix (ISSUE 12): "name[:weight]" entries expand
    # into a deterministic assignment pattern so the same command line
    # always produces the same mix.
    class_pattern: list[str] = []
    for entry in getattr(args, "slo_classes", None) or ():
        name, _, weight = entry.partition(":")
        try:
            w = max(int(weight), 1) if weight else 1
        except ValueError:
            raise SystemExit(
                f"--slo-class weight must be an integer: {entry!r}"
            )
        class_pattern.extend([name] * w)
    per_class: dict[str, dict] = {
        cls: {"ttfts": [], "itls": [], "completed": 0, "shed": 0}
        for cls in class_pattern
    }
    for t in tenant_runs:
        # Tenant classes join the per-class readout (several tenants
        # may share one SLO class — the server judges by class).
        per_class.setdefault(
            t["profile"]["slo_class"],
            {"ttfts": [], "itls": [], "completed": 0, "shed": 0},
        )

    def class_for(i: int) -> str | None:
        if not class_pattern:
            return None
        return class_pattern[i % len(class_pattern)]

    def parse_summed_metrics(text: str) -> dict:
        want = {
            "vllm:time_to_first_token_seconds_sum",
            "vllm:time_to_first_token_seconds_count",
            "vllm:time_per_output_token_seconds_sum",
            "vllm:time_per_output_token_seconds_count",
            "vllm:generation_tokens_total",
            "vllm:pipeline_breaks_total",
            "vllm:requests_rejected_total",
            # Router affinity A/B (ISSUE 10): the hit-rate delta between
            # --shared-prefix-len runs under affinity vs round_robin
            # routing is the placement-quality signal.  Scraping the
            # router sums these across replicas (the merged exposition
            # keeps per-replica labels; the sum is what A/B needs).
            "vllm:prefix_cache_queries_total",
            "vllm:prefix_cache_hits_total",
            # Fleet sentinel (ISSUE 20): alert count summed across
            # kinds, plus the burn-rate high-water gauge.
            "vdt_router:alerts_total",
            "vdt_router:fleet_slo_burn_rate_peak",
        }
        # Router resilience counters (ISSUE 19): kept split by outcome
        # label so retries granted/denied and hedge outcomes report as
        # separate columns.
        labeled = {
            "vdt_router:retries_total",
            "vdt_router:hedges_total",
            "vdt_router:breaker_rejections_total",
        }
        import re

        out = {}
        for line in text.splitlines():
            if line.startswith("#"):
                continue
            parts = line.split()
            if len(parts) != 2:
                continue
            key = parts[0].split("{")[0]
            if key in want:
                out[key] = out.get(key, 0.0) + float(parts[1])
            elif key in labeled:
                m = re.search(r'outcome="([^"]*)"', parts[0])
                k = f"{key}|{m.group(1)}" if m else key
                out[k] = out.get(k, 0.0) + float(parts[1])
        return out

    # Per-class server counters (ISSUE 12): deltas of the labeled SLO
    # families over the run window give server-judged attainment; the
    # merged router exposition sums replicas per class, which is what
    # the fleet readout needs.
    _SLO_FAMILIES = {
        "vllm:slo_requests_total",
        "vllm:goodput_requests_total",
        "vllm:slo_ttft_attained_total",
        "vllm:slo_itl_attained_total",
    }

    def parse_slo_metrics(text: str) -> dict:
        import re

        out: dict[str, dict[str, float]] = {}
        for line in text.splitlines():
            if line.startswith("#"):
                continue
            parts = line.split()
            if len(parts) != 2 or parts[0].split("{")[0] not in _SLO_FAMILIES:
                continue
            m = re.search(r'slo_class="([^"]*)"', parts[0])
            cls = m.group(1) if m else "default"
            fam = out.setdefault(parts[0].split("{")[0], {})
            fam[cls] = fam.get(cls, 0.0) + float(parts[1])
        return out

    async def scrape_metrics(session) -> tuple[dict, dict]:
        """ONE /metrics fetch parsed for both the summed throughput
        families and the per-class SLO families — a second fetch would
        double the scrape load the bench puts on the server it is
        measuring."""
        try:
            async with session.get(f"{url}/metrics") as r:
                text = await r.text()
        except Exception:  # noqa: BLE001 — metrics are optional
            return {}, {}
        return (
            parse_summed_metrics(text),
            parse_slo_metrics(text) if per_class else {},
        )

    shared_prefix_len = getattr(args, "shared_prefix_len", 0) or 0
    shared_prefix = [(7 * j) % 900 + 1 for j in range(shared_prefix_len)]

    async def drive_one(
        session,
        i: int,
        seg: dict | None = None,
        ten: dict | None = None,
    ) -> None:
        nonlocal out_tokens
        if ten is not None:
            p = ten["profile"]
            input_len = ten["len_rng"].randint(*p["input"])
            output_len = ten["len_rng"].randint(*p["output"])
            slo_class = p["slo_class"]
        else:
            input_len, output_len = args.input_len, args.output_len
            slo_class = class_for(i)
        prompt = shared_prefix + [
            (13 * i + j) % 900 + 1 for j in range(input_len)
        ]
        body = {
            "model": args.model or "bench",
            "prompt": prompt,
            "max_tokens": output_len,
            "temperature": 0.0,
            "ignore_eos": True,
            "stream": True,
            # Final chunk carries usage: tokens are counted from the
            # stream itself, not assumed = output_len (a truncated or
            # errored stream must not overstate throughput).
            "stream_options": {"include_usage": True},
        }
        if getattr(args, "deadline_ms", None):
            body["deadline_ms"] = args.deadline_ms
        if slo_class is not None:
            body["slo_class"] = slo_class
        t0 = time.perf_counter()
        chunk_times: list[float] = []
        got_tokens = 0
        finish_reason = None
        try:
            async with session.post(
                f"{url}/v1/completions", json=body
            ) as resp:
                if resp.status == 429:
                    # Load shed: an accounted outcome, not an error and
                    # not a latency sample.
                    counts["rejected"] += 1
                    if seg is not None:
                        seg["rejected"] += 1
                    if ten is not None:
                        ten["rejected"] += 1
                    await resp.read()
                    return
                if resp.status == 503:
                    # Breaker rejection / no routable replica (ISSUE
                    # 19): its own outcome column, apart from generic
                    # transport errors.
                    counts["unavailable"] += 1
                    if seg is not None:
                        seg["unavailable"] += 1
                    if ten is not None:
                        ten["unavailable"] += 1
                    await resp.read()
                    return
                resp.raise_for_status()
                async for raw in resp.content:
                    line = raw.decode().strip()
                    if not line.startswith("data:"):
                        continue
                    payload = line[5:].strip()
                    if payload == "[DONE]":
                        break
                    chunk = json.loads(payload)
                    usage = chunk.get("usage")
                    if usage:
                        got_tokens = usage.get(
                            "completion_tokens", got_tokens
                        )
                    choices = chunk.get("choices") or []
                    choice = choices[0] if choices else None
                    if choice is not None and choice.get("finish_reason"):
                        finish_reason = choice["finish_reason"]
                    # Token-bearing chunks: anything before the finish
                    # marker ("text" may be empty when the server runs
                    # without a tokenizer, e.g. dummy-weight benches).
                    # A request whose whole completion lands in ONE
                    # finish-bearing chunk (stream starved while the
                    # engine raced ahead) still delivered its first
                    # token THEN — count it, or cold requests silently
                    # vanish from the client TTFT distribution.
                    if choice is not None and (
                        not choice.get("finish_reason")
                        or not chunk_times
                    ):
                        chunk_times.append(time.perf_counter())
        except Exception:  # noqa: BLE001 — bench client: count, move on
            counts["errors"] += 1
            if seg is not None:
                seg["errors"] += 1
            if ten is not None:
                ten["errors"] += 1
            return
        if finish_reason in ("timeout", "overloaded"):
            # Deadline/pressure shed mid-generation: partial output —
            # keep it out of the completed-latency distribution too.
            counts["timed_out"] += 1
            if seg is not None:
                seg["timed_out"] += 1
            if ten is not None:
                ten["timed_out"] += 1
            if slo_class is not None:
                per_class[slo_class]["shed"] += 1
            return
        counts["completed"] += 1
        if seg is not None:
            seg["completed"] += 1
        if ten is not None:
            ten["completed"] += 1
        if slo_class is not None:
            per_class[slo_class]["completed"] += 1
        if chunk_times:
            ttft = chunk_times[0] - t0
            ttfts.append(ttft)
            out_tokens += got_tokens
            itl = None
            if got_tokens > 1:
                # Client-side per-token interval: tokens arrive in fused
                # bursts, so spread the span over the tokens after the
                # first (the serving ITL definition).
                span = chunk_times[-1] - chunk_times[0]
                itl = span / (got_tokens - 1)
                itls.append(itl)
            if seg is not None:
                seg["ttfts"].append(ttft)
                if itl is not None:
                    seg["itls"].append(itl)
            if ten is not None:
                ten["ttfts"].append(ttft)
                if itl is not None:
                    ten["itls"].append(itl)
            if slo_class is not None:
                per_class[slo_class]["ttfts"].append(ttft)
                if itl is not None:
                    per_class[slo_class]["itls"].append(itl)

    async def one(session, i: int, seg: dict | None = None) -> None:
        if request_rate is not None or seg is not None:
            # Open loop: arrivals don't wait for departures — offered
            # load is what the operator configured, not what the
            # server can absorb.
            await drive_one(session, i, seg)
        else:
            async with sem:
                await drive_one(session, i)

    timeout = aiohttp.ClientTimeout(total=None, sock_read=600)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        before, slo_before = await scrape_metrics(session)
        t0 = time.perf_counter()
        if tenant_runs:
            # Multi-tenant: every profile is an independent concurrent
            # traffic source against the same deployment for a fixed
            # wall-clock window — the per-class contention workload the
            # QoS control plane is judged on.
            import itertools

            t_end = time.perf_counter() + tenant_seconds
            next_i = itertools.count()

            async def tenant_open_loop(ten: dict) -> None:
                p = ten["profile"]
                rng = ten["arr_rng"]
                n_burst = p["burst"] if p["arrival"] == "bursty" else 1
                # Burst epochs arrive Poisson at rate/burst so the
                # OFFERED rate equals the configured rate either way —
                # bursty just concentrates it into spikes.
                epoch_rate = p["rate"] / n_burst
                tasks = []
                while time.perf_counter() < t_end:
                    for _ in range(n_burst):
                        ten["offered"] += 1
                        tasks.append(
                            asyncio.create_task(
                                drive_one(session, next(next_i), ten=ten)
                            )
                        )
                    remaining = t_end - time.perf_counter()
                    if remaining <= 0:
                        break
                    await asyncio.sleep(
                        min(rng.expovariate(epoch_rate), remaining)
                    )
                await asyncio.gather(*tasks)

            async def tenant_closed_loop(ten: dict) -> None:
                async def worker() -> None:
                    while time.perf_counter() < t_end:
                        ten["offered"] += 1
                        await drive_one(session, next(next_i), ten=ten)

                await asyncio.gather(
                    *(
                        worker()
                        for _ in range(ten["profile"]["concurrency"])
                    )
                )

            await asyncio.gather(
                *(
                    tenant_closed_loop(t)
                    if t["profile"]["arrival"] == "closed"
                    else tenant_open_loop(t)
                    for t in tenant_runs
                )
            )
        elif ramp_segments is not None:
            rng = random.Random(seed)  # reproducible arrival process
            tasks = []
            i = 0
            for seg in seg_stats:
                seg_t0 = time.perf_counter()
                rate, dur = seg["rate_rps"], seg["seconds"]
                while True:
                    remaining = dur - (time.perf_counter() - seg_t0)
                    if remaining <= 0:
                        break
                    if rate <= 0:
                        # Idle dwell: no arrivals, just hold the clock
                        # (the settle tail of a scale-down assertion).
                        await asyncio.sleep(remaining)
                        break
                    seg["offered"] += 1
                    tasks.append(
                        asyncio.create_task(one(session, i, seg))
                    )
                    i += 1
                    await asyncio.sleep(
                        min(rng.expovariate(rate), remaining)
                    )
            await asyncio.gather(*tasks)
        elif request_rate is not None:
            rng = random.Random(seed)  # reproducible arrival process
            tasks = []
            for i in range(args.num_prompts):
                tasks.append(asyncio.create_task(one(session, i)))
                await asyncio.sleep(rng.expovariate(request_rate))
            await asyncio.gather(*tasks)
        else:
            await asyncio.gather(
                *(one(session, i) for i in range(args.num_prompts))
            )
        elapsed = time.perf_counter() - t0
        after, slo_after = await scrape_metrics(session)

    if tenant_runs:
        total_requests = sum(t["offered"] for t in tenant_runs)
    elif ramp_segments is not None:
        total_requests = sum(s["offered"] for s in seg_stats)
    else:
        total_requests = args.num_prompts
    result = {
        "mode": "serve",
        "url": url,
        "num_prompts": total_requests,
        "concurrency": (
            args.concurrency
            if request_rate is None
            and ramp_segments is None
            and not tenant_runs
            else None
        ),
        # Tenant runs carry per-profile length distributions instead of
        # the global fixed lengths.
        "input_len": None if tenant_runs else getattr(args, "input_len", None),
        "output_len": None if tenant_runs else getattr(args, "output_len", None),
        "elapsed_s": round(elapsed, 3),
        "output_tokens_per_s": round(out_tokens / elapsed, 1),
        "requests_per_s": round(total_requests / elapsed, 3),
        # Latency percentiles cover COMPLETED requests only; sheds are
        # reported in outcomes below.
        "ttft_s": _percentiles(ttfts) if ttfts else None,
        "itl_ms": (
            {k: round(v * 1e3, 3) for k, v in _percentiles(itls).items()}
            if itls
            else None
        ),
        "outcomes": dict(counts),
    }
    if request_rate is not None:
        result["offered_rps"] = request_rate
        result["arrival_process"] = "poisson"
    if request_rate is not None or ramp_segments is not None or tenant_runs:
        # The stochastic-load modes record their seed so a reported
        # result names the exact workload that produced it.
        result["seed"] = seed
    if tenant_runs:
        result["arrival_process"] = "multi_tenant"
        result["tenant_seconds"] = tenant_seconds
        result["tenants"] = {}
        for t in tenant_runs:
            p = t["profile"]
            entry: dict = {
                "class": p["slo_class"],
                "arrival": p["arrival"],
                "input": list(p["input"]),
                "output": list(p["output"]),
                "offered": t["offered"],
                "completed": t["completed"],
                "rejected": t["rejected"],
                "timed_out": t["timed_out"],
                "unavailable": t["unavailable"],
                "errors": t["errors"],
                "ttft_s": (
                    _percentiles(t["ttfts"]) if t["ttfts"] else None
                ),
                "itl_ms": (
                    {
                        k: round(v * 1e3, 3)
                        for k, v in _percentiles(t["itls"]).items()
                    }
                    if t["itls"]
                    else None
                ),
            }
            if p["arrival"] == "closed":
                entry["concurrency"] = p["concurrency"]
            else:
                entry["rate_rps"] = p["rate"]
                if p["arrival"] == "bursty":
                    entry["burst"] = p["burst"]
            result["tenants"][p["name"]] = entry
    if ramp_segments is not None:
        # Per-segment readout: the rate sweep with each segment's
        # client-side percentiles and shed accounting — what the
        # autoscaler acceptance run (and the chaos ramp harness) judge.
        result["arrival_process"] = "poisson_ramp"
        result["ramp"] = [
            {
                "rate_rps": s["rate_rps"],
                "seconds": s["seconds"],
                "offered": s["offered"],
                "completed": s["completed"],
                "rejected": s["rejected"],
                "timed_out": s["timed_out"],
                "unavailable": s["unavailable"],
                "errors": s["errors"],
                "ttft_s": _percentiles(s["ttfts"]) if s["ttfts"] else None,
                "itl_ms": (
                    {
                        k: round(v * 1e3, 3)
                        for k, v in _percentiles(s["itls"]).items()
                    }
                    if s["itls"]
                    else None
                ),
            }
            for s in seg_stats
        ]
    if per_class:
        # Per-class attainment readout (ISSUE 12): client percentiles
        # plus the server's own goodput judgment over the run window.
        def slo_delta(family: str, cls: str) -> float:
            return (slo_after.get(family) or {}).get(cls, 0.0) - (
                slo_before.get(family) or {}
            ).get(cls, 0.0)

        result["per_class"] = {}
        for cls, st in per_class.items():
            entry: dict = {
                "completed": st["completed"],
                "shed": st["shed"],
                "ttft_s": (
                    _percentiles(st["ttfts"]) if st["ttfts"] else None
                ),
                "itl_ms": (
                    {
                        k: round(v * 1e3, 3)
                        for k, v in _percentiles(st["itls"]).items()
                    }
                    if st["itls"]
                    else None
                ),
            }
            reqs = slo_delta("vllm:slo_requests_total", cls)
            if reqs > 0:
                entry["server_goodput"] = slo_delta(
                    "vllm:goodput_requests_total", cls
                )
                entry["server_goodput_ratio"] = round(
                    entry["server_goodput"] / reqs, 4
                )
                entry["server_ttft_attain_ratio"] = round(
                    slo_delta("vllm:slo_ttft_attained_total", cls) / reqs,
                    4,
                )
                entry["server_itl_attain_ratio"] = round(
                    slo_delta("vllm:slo_itl_attained_total", cls) / reqs,
                    4,
                )
            result["per_class"][cls] = entry
    if (
        itls
        and request_rate is None
        and ramp_segments is None
        and not tenant_runs
    ):
        # The dispatch tax as the CLIENT sees it (ISSUE 7): throughput
        # implied by the p50 inter-token pace at this concurrency minus
        # the wall-clock throughput.  ~0 when the driver holds the p50
        # pace for the whole run.  Closed-loop only (open-loop
        # concurrency is not a constant).
        itl_p50 = _percentiles(itls)["p50"]
        if itl_p50 > 0:
            result["wall_vs_p50_gap"] = round(
                args.concurrency / itl_p50 - result["output_tokens_per_s"],
                1,
            )
    if after:
        # Server-side cross-check: deltas of the Prometheus histograms
        # over the run window.
        def delta(key):
            return after.get(key, 0.0) - before.get(key, 0.0)

        ttft_n = delta("vllm:time_to_first_token_seconds_count")
        itl_n = delta("vllm:time_per_output_token_seconds_count")
        result["server_metrics"] = {
            "ttft_mean_s": round(
                delta("vllm:time_to_first_token_seconds_sum")
                / max(ttft_n, 1),
                4,
            ),
            "itl_mean_ms": round(
                delta("vllm:time_per_output_token_seconds_sum")
                / max(itl_n, 1)
                * 1e3,
                3,
            ),
            "generation_tokens": delta("vllm:generation_tokens_total"),
            # Cross-check: the server's own 429 count over the window
            # should match the client's rejected outcome.
            "requests_rejected": delta("vllm:requests_rejected_total"),
        }
        # Resilience columns (ISSUE 19): present whenever the scrape
        # target exposes the router families (i.e. --url points at a
        # router, not a bare replica).
        if any(k.startswith("vdt_router:") for k in after):
            result["server_metrics"]["router_resilience"] = {
                "retries_granted": int(
                    delta("vdt_router:retries_total|granted")
                ),
                "retries_denied": int(
                    delta("vdt_router:retries_total|denied")
                ),
                "hedges": int(
                    sum(
                        delta(k)
                        for k in set(after) | set(before)
                        if k.startswith("vdt_router:hedges_total|")
                        and not k.endswith("|denied")
                    )
                ),
                "hedges_denied": int(
                    delta("vdt_router:hedges_total|denied")
                ),
                "breaker_rejections": int(
                    delta("vdt_router:breaker_rejections_total")
                ),
            }
            # Sentinel columns (ISSUE 20): alerts fired over the run
            # window and the burn-rate high-water mark (a gauge — the
            # end-of-run value IS the peak, no delta).
            result["server_metrics"]["alerts_fired"] = int(
                delta("vdt_router:alerts_total")
            )
            result["server_metrics"]["peak_fleet_slo_burn_rate"] = round(
                after.get("vdt_router:fleet_slo_burn_rate_peak", 0.0), 3
            )
        queries = delta("vllm:prefix_cache_queries_total")
        hits = delta("vllm:prefix_cache_hits_total")
        if queries > 0:
            # The affinity A/B readout: run once with the router in
            # affinity mode and once in round_robin; the shared-prefix
            # workload should show a higher hit rate under affinity.
            result["server_metrics"]["prefix_cache_hit_rate"] = round(
                hits / queries, 4
            )
            result["server_metrics"]["prefix_cache_hits"] = hits
        if shared_prefix_len:
            result["shared_prefix_len"] = shared_prefix_len
        # Engine-side pipeline flushes over the run window: the serve
        # analogue of the microbench's stall_windows (0 = the async
        # scheduler never had to drain and re-plan mid-run).
        result["stall_windows"] = int(
            delta("vllm:pipeline_breaks_total")
        )
    return result


def cmd_bench(args: argparse.Namespace) -> None:
    import time

    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams

    if args.mode == "serve":
        print(json.dumps(asyncio.run(_bench_serve_async(args))))
        return

    engine_args = EngineArgs.from_cli_args(args)
    engine = LLMEngine.from_engine_args(engine_args)
    sp = SamplingParams(
        temperature=0.0, max_tokens=args.output_len, ignore_eos=True
    )
    vocab = engine.config.model_config.get_vocab_size()
    prompts = [
        [(13 * i + j) % (vocab - 10) + 1 for j in range(args.input_len)]
        for i in range(args.num_prompts)
    ]
    if args.mode == "latency":
        # One request at a time; report per-request latency.
        lat = []
        for i, p in enumerate(prompts[: min(8, len(prompts))]):
            t0 = time.perf_counter()
            engine.add_request(f"b{i}", prompt_token_ids=p, sampling_params=sp)
            while engine.has_unfinished_requests():
                engine.step()
            lat.append(time.perf_counter() - t0)
        lat.sort()
        print(
            json.dumps(
                {
                    "mode": "latency",
                    "p50_s": round(lat[len(lat) // 2], 4),
                    "mean_s": round(sum(lat) / len(lat), 4),
                    "output_len": args.output_len,
                }
            )
        )
    else:
        for i, p in enumerate(prompts):
            engine.add_request(f"b{i}", prompt_token_ids=p, sampling_params=sp)
        t0 = time.perf_counter()
        done = 0
        while engine.has_unfinished_requests():
            done += sum(1 for o in engine.step() if o.finished)
        elapsed = time.perf_counter() - t0
        total_tokens = args.num_prompts * (args.input_len + args.output_len)
        print(
            json.dumps(
                {
                    "mode": "throughput",
                    "requests_per_s": round(args.num_prompts / elapsed, 3),
                    "total_tokens_per_s": round(total_tokens / elapsed, 1),
                    "output_tokens_per_s": round(
                        args.num_prompts * args.output_len / elapsed, 1
                    ),
                    "elapsed_s": round(elapsed, 2),
                }
            )
        )


# ---- collect-env ----
def cmd_collect_env(args: argparse.Namespace) -> None:
    import platform

    info = {
        "vdt": __version__,
        "python": sys.version.split()[0],
        "platform": platform.platform(),
    }
    for mod in ("jax", "jaxlib", "flax", "numpy", "transformers", "aiohttp"):
        try:
            info[mod] = __import__(mod).__version__
        except Exception:  # noqa: BLE001
            info[mod] = "unavailable"
    try:
        import jax

        info["backend"] = jax.default_backend()
        info["devices"] = [str(d) for d in jax.devices()]
    except Exception as e:  # noqa: BLE001
        info["backend"] = f"error: {e}"
    from vllm_distributed_tpu import envs

    info["env"] = {
        k: str(v())
        for k, v in envs.environment_variables.items()
        if k in os.environ
    }
    print(json.dumps(info, indent=2))


# ---- run-batch ----
def cmd_run_batch(args: argparse.Namespace) -> None:
    """Each input line: {"custom_id": ..., "body": {"prompt" | "messages",
    sampling fields}} — the OpenAI batch-file shape (launch.py:25)."""
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.entrypoints.openai.protocol import (
        CompletionRequest,
    )
    from vllm_distributed_tpu.sampling_params import SamplingParams

    engine_args = EngineArgs.from_cli_args(args)
    engine = LLMEngine.from_engine_args(engine_args)
    max_len = engine.config.model_config.max_model_len

    requests = []
    with open(args.input_file) as f:
        for line in f:
            if line.strip():
                requests.append(json.loads(line))
    for i, item in enumerate(requests):
        body = item.get("body", item)
        req = CompletionRequest(**{
            k: v for k, v in body.items()
            if k in CompletionRequest.model_fields
        })
        prompt = body.get("prompt", "")
        sp = req.to_sampling_params(max_len // 2, is_chat=False)
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            engine.add_request(
                str(item.get("custom_id", i)),
                prompt_token_ids=prompt,
                sampling_params=sp,
            )
        else:
            engine.add_request(
                str(item.get("custom_id", i)),
                prompt=str(prompt),
                sampling_params=sp,
            )
    results = {}
    while engine.has_unfinished_requests():
        for out in engine.step():
            if out.finished:
                results[out.request_id] = {
                    "custom_id": out.request_id,
                    "response": {
                        "text": out.outputs[0].text,
                        "token_ids": out.outputs[0].token_ids,
                        "finish_reason": out.outputs[0].finish_reason,
                    },
                }
    with open(args.output_file, "w") as f:
        for item in requests:
            rid = str(item.get("custom_id", requests.index(item)))
            f.write(json.dumps(results.get(rid, {"custom_id": rid})) + "\n")
    logger.info("wrote %d results to %s", len(results), args.output_file)


# ---- client commands ----
def cmd_client(args: argparse.Namespace, chat: bool) -> None:
    import urllib.request

    if chat:
        body = {
            "model": args.model or "",
            "messages": [{"role": "user", "content": args.prompt or "hi"}],
        }
        path = "/v1/chat/completions"
    else:
        body = {"model": args.model or "", "prompt": args.prompt or "hi"}
        path = "/v1/completions"
    req = urllib.request.Request(
        args.url + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        out = json.loads(resp.read())
    if chat:
        print(out["choices"][0]["message"]["content"])
    else:
        print(out["choices"][0]["text"])


def main(argv: list[str] | None = None) -> None:
    argv = _expand_env(argv if argv is not None else sys.argv[1:])
    args = make_parser().parse_args(argv)
    if args.command == "serve":
        cmd_serve(args)
    elif args.command == "remote":
        cmd_remote(args)
    elif args.command == "router":
        cmd_router(args)
    elif args.command == "bench":
        cmd_bench(args)
    elif args.command == "collect-env":
        cmd_collect_env(args)
    elif args.command == "run-batch":
        cmd_run_batch(args)
    elif args.command == "chat":
        cmd_client(args, chat=True)
    elif args.command == "complete":
        cmd_client(args, chat=False)


if __name__ == "__main__":
    main()
