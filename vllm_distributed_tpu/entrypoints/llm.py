"""Offline batch inference API: ``LLM("model").generate(prompts)``.

The Python-native front door (the capability the reference gets from
vLLM's `LLM` class / `run_batch` CLI, SURVEY.md §2.3).
"""

from __future__ import annotations

from typing import Sequence

from vllm_distributed_tpu.config import EngineArgs
from vllm_distributed_tpu.engine.llm_engine import LLMEngine
from vllm_distributed_tpu.outputs import RequestOutput
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import Counter


class LLM:
    def __init__(self, model: str, **kwargs) -> None:
        engine_args = EngineArgs(model=model, **kwargs)
        self.engine = LLMEngine.from_engine_args(engine_args)
        self._counter = Counter()

    def generate(
        self,
        prompts: str | Sequence[str] | None = None,
        sampling_params: SamplingParams | Sequence[SamplingParams] | None = None,
        prompt_token_ids: Sequence[list[int]] | None = None,
    ) -> list[RequestOutput]:
        if isinstance(prompts, str):
            prompts = [prompts]
        n = len(prompts) if prompts is not None else len(prompt_token_ids)
        if sampling_params is None:
            sampling_params = SamplingParams()
        if isinstance(sampling_params, SamplingParams):
            sampling_params = [sampling_params] * n

        req_ids = []
        for i in range(n):
            req_id = f"llm-{next(self._counter)}"
            req_ids.append(req_id)
            self.engine.add_request(
                req_id,
                prompt=prompts[i] if prompts is not None else None,
                prompt_token_ids=(
                    list(prompt_token_ids[i])
                    if prompt_token_ids is not None
                    else None
                ),
                sampling_params=sampling_params[i],
            )

        results: dict[str, RequestOutput] = {}
        while self.engine.has_unfinished_requests():
            for out in self.engine.step():
                if out.finished:
                    results[out.request_id] = out
        return [results[r] for r in req_ids]
