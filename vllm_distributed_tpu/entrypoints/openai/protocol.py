"""OpenAI-compatible API schema (pydantic).

The request/response surface the reference serves via vLLM's OpenAI app
(build_app/init_app_state, launch.py:32-34, 429-432; SURVEY.md §2.3):
chat completions, completions, models, tokenize — with the sampling
fields mapped onto SamplingParams.
"""

from __future__ import annotations

import time
from typing import Any, Literal

from pydantic import BaseModel, Field

from vllm_distributed_tpu.sampling_params import SamplingParams


class ModelCard(BaseModel):
    id: str
    object: str = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "vllm-distributed-tpu"
    max_model_len: int | None = None


class ModelList(BaseModel):
    object: str = "list"
    data: list[ModelCard] = []


class ErrorResponse(BaseModel):
    object: str = "error"
    message: str
    type: str = "invalid_request_error"
    code: int = 400


class ChatMessage(BaseModel):
    role: str
    content: str | list[dict] | None = None
    name: str | None = None
    tool_calls: list[dict] | None = None
    tool_call_id: str | None = None


class _SamplingFields(BaseModel):
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None
    min_p: float | None = None
    n: int = 1
    max_tokens: int | None = None
    min_tokens: int = 0
    stop: str | list[str] | None = None
    stop_token_ids: list[int] | None = None
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    logprobs: bool | int | None = None
    top_logprobs: int | None = None
    seed: int | None = None
    ignore_eos: bool = False
    stream: bool = False
    stream_options: dict | None = None
    skip_special_tokens: bool = True
    include_stop_str_in_output: bool = False
    # Per-request deadline in ms from arrival (the X-VDT-Deadline-Ms
    # header sets it too; an explicit body field wins).  None = server
    # default.
    deadline_ms: int | None = None
    # SLO class for goodput accounting (ISSUE 12; the X-VDT-SLO-Class
    # header sets it too; an explicit body field wins — None means
    # "not sent", so a client explicitly naming "default" still beats
    # the header).  Sanitized and cardinality-bounded server-side.
    slo_class: str | None = None

    def to_sampling_params(
        self, default_max_tokens: int, is_chat: bool
    ) -> SamplingParams:
        stop = self.stop
        if isinstance(stop, str):
            stop = [stop]
        if is_chat:
            nlp = (
                self.top_logprobs
                if self.logprobs
                else None
            )
            if self.logprobs and nlp is None:
                nlp = 1
        else:
            nlp = self.logprobs if isinstance(self.logprobs, int) else None
        return SamplingParams(
            n=self.n,
            temperature=(
                self.temperature if self.temperature is not None else 1.0
            ),
            top_p=self.top_p if self.top_p is not None else 1.0,
            top_k=self.top_k if self.top_k is not None else -1,
            min_p=self.min_p if self.min_p is not None else 0.0,
            max_tokens=(
                self.max_tokens
                if self.max_tokens is not None
                else default_max_tokens
            ),
            min_tokens=self.min_tokens,
            stop=stop or [],
            stop_token_ids=self.stop_token_ids or [],
            presence_penalty=self.presence_penalty,
            frequency_penalty=self.frequency_penalty,
            repetition_penalty=self.repetition_penalty,
            logprobs=nlp,
            seed=self.seed,
            ignore_eos=self.ignore_eos,
            include_stop_str_in_output=self.include_stop_str_in_output,
            deadline_ms=self.deadline_ms,
            slo_class=self.slo_class or "default",
        )


class ChatCompletionRequest(_SamplingFields):
    model: str = ""
    messages: list[ChatMessage]
    tools: list[dict] | None = None
    tool_choice: str | dict | None = None
    chat_template: str | None = None
    chat_template_kwargs: dict[str, Any] | None = None
    add_generation_prompt: bool = True


class CompletionRequest(_SamplingFields):
    model: str = ""
    prompt: str | list[str] | list[int] | list[list[int]] = ""
    echo: bool = False


class UsageInfo(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class ToolCall(BaseModel):
    id: str
    type: str = "function"
    function: dict


class ChatResponseMessage(BaseModel):
    role: str = "assistant"
    content: str | None = None
    tool_calls: list[ToolCall] | None = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatResponseMessage
    logprobs: dict | None = None
    finish_reason: str | None = None


class ChatCompletionResponse(BaseModel):
    id: str
    object: str = "chat.completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str
    choices: list[ChatChoice]
    usage: UsageInfo = UsageInfo()


class ChatDelta(BaseModel):
    role: str | None = None
    content: str | None = None
    tool_calls: list[dict] | None = None


class ChatStreamChoice(BaseModel):
    index: int = 0
    delta: ChatDelta
    finish_reason: str | None = None


class ChatCompletionStreamResponse(BaseModel):
    id: str
    object: str = "chat.completion.chunk"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str
    choices: list[ChatStreamChoice]
    usage: UsageInfo | None = None


class CompletionChoice(BaseModel):
    index: int = 0
    text: str
    logprobs: dict | None = None
    finish_reason: str | None = None


class CompletionResponse(BaseModel):
    id: str
    object: str = "text_completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str
    choices: list[CompletionChoice]
    usage: UsageInfo = UsageInfo()


class TokenizeRequest(BaseModel):
    model: str = ""
    prompt: str = ""
    add_special_tokens: bool = True


class TokenizeResponse(BaseModel):
    tokens: list[int]
    count: int
    max_model_len: int


class DetokenizeRequest(BaseModel):
    model: str = ""
    tokens: list[int]


class DetokenizeResponse(BaseModel):
    prompt: str


class EmbeddingRequest(BaseModel):
    model: str = ""
    input: str | list[str] | list[int] | list[list[int]] = ""
    encoding_format: str = "float"
    user: str | None = None


class EmbeddingData(BaseModel):
    object: str = "embedding"
    index: int = 0
    # list[float], or a base64 string when encoding_format="base64"
    # (the openai-python client's default).
    embedding: list[float] | str


class EmbeddingResponse(BaseModel):
    object: str = "list"
    model: str
    data: list[EmbeddingData]
    usage: UsageInfo = UsageInfo()
