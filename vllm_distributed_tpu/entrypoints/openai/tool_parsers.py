"""Pluggable tool-call parsers.

The reference exposes vLLM's ToolParserManager with
``--tool-parser-plugin`` / ``--tool-call-parser`` (launch.py:38, 417-418;
.env.server:11 uses ``qwen3_coder``; SURVEY.md §2.3).  Same shape here: a
registry keyed by name, an import hook for user plugin files, and
built-in parsers for the common tag formats.
"""

from __future__ import annotations

import json
import re
import uuid
from typing import Any

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


class ToolParser:
    """Extract tool calls from finished model output.  Returns
    (content_without_tool_text, [tool_call dicts])."""

    def extract(self, text: str) -> tuple[str | None, list[dict]]:
        raise NotImplementedError

    def streaming(self) -> "StreamingToolParser":
        """A fresh per-request incremental parser (SSE tool-call
        deltas).  Default: block-granular streaming (whole calls emit
        as each ``<tool_call>`` block closes); format-aware subclasses
        stream finer fragments."""
        return StreamingToolParser(self)


class StreamingToolParser:
    """Incremental tool-call parsing over a text stream.

    ``push(text_delta)`` returns ``(content_delta, tool_deltas)``:
    plain text outside tool blocks streams through immediately (minus
    any suffix that could begin a block marker), and tool-call
    fragments follow the OpenAI streaming shape — each dict carries
    ``index`` plus, on its first fragment, ``id``/``type`` and the
    function name; ``function.arguments`` fragments CONCATENATE to the
    full JSON arguments string.  ``finish()`` flushes: an unterminated
    block is surfaced back as plain content (a truncated call is not a
    call).

    This base class emits each call whole once its block closes —
    correct for any registered format via ``extract``.  The flagship
    qwen3_coder format gets parameter-granular deltas
    (Qwen3CoderStreamingParser)."""

    START = "<tool_call>"
    END = "</tool_call>"

    def __init__(self, parser: ToolParser) -> None:
        self.parser = parser
        self._buf = ""
        self._in_block = False
        self._index = 0
        self.saw_tool_call = False

    @staticmethod
    def _partial_suffix(text: str, marker: str) -> int:
        """Length of the longest tail of ``text`` that is a proper
        prefix of ``marker`` (must be held back, it may grow into the
        marker)."""
        for n in range(min(len(marker) - 1, len(text)), 0, -1):
            if text.endswith(marker[:n]):
                return n
        return 0

    # ---- hooks for subclasses ----
    def _consume_block(self) -> list[dict] | None:
        """Try to consume tool content at the head of the buffer (which
        starts with START).  Returns fragments, or None to wait for
        more text.  Must leave the buffer past everything consumed and
        reset _in_block when the block closed."""
        end = self._buf.find(self.END)
        if end < 0:
            return None
        block = self._buf[: end + len(self.END)]
        self._buf = self._buf[end + len(self.END) :]
        self._in_block = False
        _, calls = self.parser.extract(block)
        out = []
        for call in calls:
            out.append({"index": self._index, **call})
            self._index += 1
        return out

    def push(self, delta: str) -> tuple[str, list[dict]]:
        self._buf += delta
        content: list[str] = []
        tools: list[dict] = []
        while True:
            if not self._in_block:
                i = self._buf.find(self.START)
                if i < 0:
                    keep = self._partial_suffix(self._buf, self.START)
                    cut = len(self._buf) - keep
                    if cut > 0:
                        content.append(self._buf[:cut])
                        self._buf = self._buf[cut:]
                    break
                content.append(self._buf[:i])
                self._buf = self._buf[i:]
                self._in_block = True
                self.saw_tool_call = True
            frags = self._consume_block()
            if frags is None:
                break
            tools.extend(frags)
        return "".join(content), tools

    def finish(self) -> tuple[str, list[dict]]:
        """End of stream: unterminated tool text degrades to content."""
        content, tools = self._buf, []
        self._buf = ""
        self._in_block = False
        return content, tools


class ToolParserManager:
    _parsers: dict[str, type[ToolParser]] = {}

    @classmethod
    def register(cls, name: str):
        def deco(parser_cls):
            cls._parsers[name] = parser_cls
            return parser_cls

        return deco

    @classmethod
    def get(cls, name: str) -> ToolParser:
        try:
            return cls._parsers[name]()
        except KeyError:
            raise ValueError(
                f"unknown tool parser {name!r}; known: {sorted(cls._parsers)}"
            ) from None

    @classmethod
    def import_tool_parser(cls, plugin_path: str) -> None:
        """Load a user plugin file that registers parsers (the
        --tool-parser-plugin flow, launch.py:417-418)."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "vdt_tool_parser_plugin", plugin_path
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        logger.info("loaded tool parser plugin from %s", plugin_path)


def _mk_call(name: str, arguments: Any) -> dict:
    if not isinstance(arguments, str):
        arguments = json.dumps(arguments)
    return {
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


@ToolParserManager.register("hermes")
@ToolParserManager.register("qwen2")
class HermesToolParser(ToolParser):
    """``<tool_call>{"name": ..., "arguments": {...}}</tool_call>`` blocks
    (Hermes/Qwen chat formats)."""

    _RE = re.compile(r"<tool_call>\s*(\{.*?\})\s*</tool_call>", re.DOTALL)

    def extract(self, text: str) -> tuple[str | None, list[dict]]:
        calls = []
        for m in self._RE.finditer(text):
            try:
                obj = json.loads(m.group(1))
                calls.append(
                    _mk_call(obj.get("name", ""), obj.get("arguments", {}))
                )
            except json.JSONDecodeError:
                logger.warning("unparseable tool_call block ignored")
        if not calls:
            return text, []
        content = self._RE.sub("", text).strip() or None
        return content, calls


@ToolParserManager.register("qwen3_coder")
class Qwen3CoderToolParser(ToolParser):
    """Qwen3-Coder XML-ish format:
    <tool_call><function=NAME><parameter=KEY>VALUE</parameter>...
    </function></tool_call> (the parser named in .env.server:11)."""

    _BLOCK = re.compile(r"<tool_call>(.*?)</tool_call>", re.DOTALL)
    _FN = re.compile(r"<function=([^>]+)>(.*?)</function>", re.DOTALL)
    _PARAM = re.compile(r"<parameter=([^>]+)>(.*?)</parameter>", re.DOTALL)

    def extract(self, text: str) -> tuple[str | None, list[dict]]:
        calls = []
        for block in self._BLOCK.finditer(text):
            for fn in self._FN.finditer(block.group(1)):
                name = fn.group(1).strip()
                params = {
                    p.group(1).strip(): _coerce(p.group(2).strip())
                    for p in self._PARAM.finditer(fn.group(2))
                }
                calls.append(_mk_call(name, params))
        if not calls:
            return text, []
        content = self._BLOCK.sub("", text).strip() or None
        return content, calls

    def streaming(self) -> "Qwen3CoderStreamingParser":
        return Qwen3CoderStreamingParser(self)


class Qwen3CoderStreamingParser(StreamingToolParser):
    """Parameter-granular streaming for the qwen3_coder XML-ish format
    (the parser the reference's flagship COMMAND names,
    .env.server:11): the call header (id + function name) is emitted as
    soon as ``<function=NAME>`` closes its ``>``, and each completed
    ``<parameter=K>V</parameter>`` emits an arguments fragment — the
    fragments concatenate to the same JSON object the finished-text
    parser produces."""

    _FN_OPEN = re.compile(r"<function=([^>]+)>")
    _PARAM_ONE = re.compile(
        r"\s*<parameter=([^>]+)>(.*?)</parameter>", re.DOTALL
    )

    def __init__(self, parser: ToolParser) -> None:
        super().__init__(parser)
        self._call_open = False  # emitted header, not yet closed args
        self._nargs = 0

    def _frag(self, arguments: str) -> dict:
        return {"index": self._index, "function": {"arguments": arguments}}

    def _consume_block(self) -> list[dict] | None:
        out: list[dict] = []
        progress = True
        while progress:
            progress = False
            if not self._call_open:
                m = self._FN_OPEN.search(self._buf)
                end = self._buf.find(self.END)
                if m is None or (0 <= end < m.start()):
                    # No (further) function in this block: close it once
                    # the end tag arrives.
                    if end < 0:
                        return out or None
                    self._buf = self._buf[end + len(self.END) :]
                    self._in_block = False
                    return out
                out.append(
                    {
                        "index": self._index,
                        "id": f"call_{uuid.uuid4().hex[:24]}",
                        "type": "function",
                        "function": {"name": m.group(1).strip()},
                    }
                )
                self._buf = self._buf[m.end() :]
                self._call_open = True
                self._nargs = 0
                progress = True
                continue
            # Inside <function=...>: complete parameters stream out;
            # </function> closes the arguments object.
            pm = self._PARAM_ONE.match(self._buf)
            if pm is not None:
                key = json.dumps(pm.group(1).strip())
                val = json.dumps(_coerce(pm.group(2).strip()))
                prefix = "{" if self._nargs == 0 else ", "
                out.append(self._frag(f"{prefix}{key}: {val}"))
                self._nargs += 1
                self._buf = self._buf[pm.end() :]
                progress = True
                continue
            fn_end = self._buf.find("</function>")
            if fn_end >= 0:
                # Close the call.  Anything before the tag that is not
                # a complete parameter is malformed tool text — dropped
                # (the finished-text extract() mis-parses such bodies
                # the same way: its non-greedy regex stops at the first
                # '</function>'), but the stream must NOT wedge on it:
                # trailing content after the block has to keep flowing.
                if self._buf[:fn_end].strip():
                    logger.warning(
                        "malformed tool-call body ignored in stream"
                    )
                out.append(
                    self._frag("{}" if self._nargs == 0 else "}")
                )
                self._index += 1
                self._call_open = False
                self._buf = self._buf[fn_end + len("</function>") :]
                progress = True
                continue
            blk_end = self._buf.find(self.END)
            if blk_end >= 0:
                # </tool_call> with no </function>: close the call at
                # the block end so the outer loop can consume it.
                out.append(
                    self._frag("{}" if self._nargs == 0 else "}")
                )
                self._index += 1
                self._call_open = False
                progress = True
                continue
        return out or None

    def finish(self) -> tuple[str, list[dict]]:
        if self._call_open:
            # Truncated mid-call: close the arguments object so the
            # concatenated fragments stay valid JSON.
            frag = self._frag("{}" if self._nargs == 0 else "}")
            self._index += 1
            self._call_open = False
            self._buf = ""
            self._in_block = False
            return "", [frag]
        return super().finish()


def _coerce(value: str) -> Any:
    """Best-effort typing of string parameter values (numbers, bools,
    JSON literals pass through as their parsed type)."""
    try:
        return json.loads(value)
    except (json.JSONDecodeError, ValueError):
        return value
