"""Pluggable tool-call parsers.

The reference exposes vLLM's ToolParserManager with
``--tool-parser-plugin`` / ``--tool-call-parser`` (launch.py:38, 417-418;
.env.server:11 uses ``qwen3_coder``; SURVEY.md §2.3).  Same shape here: a
registry keyed by name, an import hook for user plugin files, and
built-in parsers for the common tag formats.
"""

from __future__ import annotations

import json
import re
import uuid
from typing import Any

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


class ToolParser:
    """Extract tool calls from finished model output.  Returns
    (content_without_tool_text, [tool_call dicts])."""

    def extract(self, text: str) -> tuple[str | None, list[dict]]:
        raise NotImplementedError


class ToolParserManager:
    _parsers: dict[str, type[ToolParser]] = {}

    @classmethod
    def register(cls, name: str):
        def deco(parser_cls):
            cls._parsers[name] = parser_cls
            return parser_cls

        return deco

    @classmethod
    def get(cls, name: str) -> ToolParser:
        try:
            return cls._parsers[name]()
        except KeyError:
            raise ValueError(
                f"unknown tool parser {name!r}; known: {sorted(cls._parsers)}"
            ) from None

    @classmethod
    def import_tool_parser(cls, plugin_path: str) -> None:
        """Load a user plugin file that registers parsers (the
        --tool-parser-plugin flow, launch.py:417-418)."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "vdt_tool_parser_plugin", plugin_path
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        logger.info("loaded tool parser plugin from %s", plugin_path)


def _mk_call(name: str, arguments: Any) -> dict:
    if not isinstance(arguments, str):
        arguments = json.dumps(arguments)
    return {
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


@ToolParserManager.register("hermes")
@ToolParserManager.register("qwen2")
class HermesToolParser(ToolParser):
    """``<tool_call>{"name": ..., "arguments": {...}}</tool_call>`` blocks
    (Hermes/Qwen chat formats)."""

    _RE = re.compile(r"<tool_call>\s*(\{.*?\})\s*</tool_call>", re.DOTALL)

    def extract(self, text: str) -> tuple[str | None, list[dict]]:
        calls = []
        for m in self._RE.finditer(text):
            try:
                obj = json.loads(m.group(1))
                calls.append(
                    _mk_call(obj.get("name", ""), obj.get("arguments", {}))
                )
            except json.JSONDecodeError:
                logger.warning("unparseable tool_call block ignored")
        if not calls:
            return text, []
        content = self._RE.sub("", text).strip() or None
        return content, calls


@ToolParserManager.register("qwen3_coder")
class Qwen3CoderToolParser(ToolParser):
    """Qwen3-Coder XML-ish format:
    <tool_call><function=NAME><parameter=KEY>VALUE</parameter>...
    </function></tool_call> (the parser named in .env.server:11)."""

    _BLOCK = re.compile(r"<tool_call>(.*?)</tool_call>", re.DOTALL)
    _FN = re.compile(r"<function=([^>]+)>(.*?)</function>", re.DOTALL)
    _PARAM = re.compile(r"<parameter=([^>]+)>(.*?)</parameter>", re.DOTALL)

    def extract(self, text: str) -> tuple[str | None, list[dict]]:
        calls = []
        for block in self._BLOCK.finditer(text):
            for fn in self._FN.finditer(block.group(1)):
                name = fn.group(1).strip()
                params = {
                    p.group(1).strip(): _coerce(p.group(2).strip())
                    for p in self._PARAM.finditer(fn.group(2))
                }
                calls.append(_mk_call(name, params))
        if not calls:
            return text, []
        content = self._BLOCK.sub("", text).strip() or None
        return content, calls


def _coerce(value: str) -> Any:
    """Best-effort typing of string parameter values (numbers, bools,
    JSON literals pass through as their parsed type)."""
    try:
        return json.loads(value)
    except (json.JSONDecodeError, ValueError):
        return value
