"""OpenAI-compatible HTTP server (aiohttp).

The rebuild of the serving layer the reference assembles from vLLM's
entrypoints (setup_server/build_app/init_app_state/serve_http,
launch.py:413-457; SURVEY.md §2 C7): chat completions, completions,
models, tokenize/detokenize, health, version, Prometheus /metrics, SSE
streaming, keep-alive timeout (VDT_HTTP_TIMEOUT_KEEP_ALIVE ≈
VLLM_HTTP_TIMEOUT_KEEP_ALIVE, launch.py:445), and the tool-parser hook
(--tool-call-parser, .env.server:11).
"""

from __future__ import annotations

import asyncio
import hmac
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any

from aiohttp import web

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.engine.async_llm import (
    AsyncLLM,
    EngineDeadError,
    EngineRecoveringError,
)
from vllm_distributed_tpu.engine.overload import EngineOverloadedError
from vllm_distributed_tpu.entrypoints.openai.protocol import (
    EmbeddingData,
    EmbeddingRequest,
    EmbeddingResponse,
    ChatChoice,
    ChatCompletionRequest,
    ChatCompletionResponse,
    ChatCompletionStreamResponse,
    ChatDelta,
    ChatMessage,
    ChatResponseMessage,
    ChatStreamChoice,
    CompletionChoice,
    CompletionRequest,
    CompletionResponse,
    DetokenizeRequest,
    DetokenizeResponse,
    ErrorResponse,
    ModelCard,
    ModelList,
    TokenizeRequest,
    TokenizeResponse,
    ToolCall,
    UsageInfo,
)
from vllm_distributed_tpu.entrypoints.openai.tool_parsers import (
    ToolParserManager,
)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.outputs import RequestOutput
from vllm_distributed_tpu.tracing import get_tracer
from vllm_distributed_tpu.utils import Counter
from vllm_distributed_tpu.version import __version__

logger = init_logger(__name__)

# Response header echoing the request's 128-bit trace id; look it up in
# /debug/traces (or your OTLP backend) to see where the latency went.
TRACE_HEADER = "X-VDT-Trace-Id"

# Request header carrying the client's deadline in milliseconds from
# arrival (the deadline_ms body field wins when both are present).
DEADLINE_HEADER = "X-VDT-Deadline-Ms"

# Request header naming the request's SLO class for goodput accounting
# (ISSUE 12; the slo_class body field wins when both are present).
# Sanitized and cardinality-bounded server-side (engine/slo.py) before
# it becomes a metric label.
SLO_CLASS_HEADER = "X-VDT-SLO-Class"

# Stable identity of this serving replica (VDT_REPLICA_ID, default
# host:port), stamped on every response so a router/bench/log reader can
# attribute behavior per replica (ISSUE 10 satellite).
REPLICA_HEADER = "X-VDT-Replica-Id"

# Internal hop marker set by the multi-replica router (router/): when
# present, streaming chunks carry per-choice ``vdt_token_ids`` (and
# ``vdt_prompt_token_ids`` on the first chunk) so the router can journal
# emitted tokens for live migration and feed its prefix-affinity index.
# The router strips these fields before the client sees them.
ROUTER_HEADER = "X-VDT-Router"

# Disaggregated prefill/decode (ISSUE 15): the router marks the
# prefill-pool hop with ``X-VDT-Disagg: prefill``, and this replica runs
# the request as prefill-only — prefill plus the first sampled token,
# then finish with the KV pages HELD for export (engine/kv_transfer.py).
# Streaming chunks then carry ``vdt_kv_handle`` (the engine request id)
# so the router can drive /internal/kv/export and /internal/kv/release.
DISAGG_HEADER = "X-VDT-Disagg"


@dataclass
class ServerState:
    engine: AsyncLLM
    model_name: str
    max_model_len: int
    tool_call_parser: str | None = None
    enable_auto_tool_choice: bool = False
    chat_template: str | None = None
    api_key: str | None = None
    replica_id: str = ""
    # Disaggregation role this replica announces in /health (ISSUE 15):
    # "prefill" | "decode" | "mixed".  Pure advertisement — the router
    # reads it from the health probe and places accordingly; the
    # replica itself serves whatever arrives.
    role: str = "mixed"
    request_counter: Counter = field(default_factory=Counter)
    metrics: Any = None
    # Live /internal/resume handler task per request id (ISSUE 17): a
    # router replaying a resume for an id it already resumed (it
    # crashed mid-hand-off and cannot know whether the first attempt
    # landed) takes over from the stale handler instead of deadlocking
    # behind its registration.
    resume_takeovers: dict = field(default_factory=dict)


# Endpoints that stay open without an API key (probes + scrapers), the
# same split vLLM's build_app auth middleware makes.  /slo is a scraper
# surface like /metrics (the router's fleet merge pulls it).
_UNAUTHENTICATED = {
    "/health", "/ping", "/version", "/metrics", "/slo",
    # The router's timeline merge scrapes this like /slo and /metrics:
    # same operational-telemetry sensitivity, same auth posture.
    "/debug/events",
}

# Probe/scrape endpoints never open a root span (they would drown the
# trace ring in noise and trace nothing request-shaped).  /drain can
# block for the full drain timeout — a span that long is noise too.
_UNTRACED = {
    "/health", "/ping", "/version", "/metrics", "/slo", "/debug/traces",
    "/debug/flightrecorder", "/debug/events", "/debug/profile", "/drain",
}


@web.middleware
async def auth_middleware(request: web.Request, handler):
    state: ServerState = request.app["state"]
    if state.api_key and request.path not in _UNAUTHENTICATED:
        header = request.headers.get("Authorization", "")
        expect = f"Bearer {state.api_key}".encode()
        got = header.encode("utf-8", "surrogateescape")
        if not hmac.compare_digest(got, expect):
            return _error("invalid or missing API key", 401)
    return await handler(request)


@web.middleware
async def replica_middleware(request: web.Request, handler):
    """Stamp X-VDT-Replica-Id on every unprepared response (streamed
    responses add it to their own headers before prepare())."""
    response = await handler(request)
    state: ServerState = request.app["state"]
    if state.replica_id and not response.prepared:
        response.headers.setdefault(REPLICA_HEADER, state.replica_id)
    return response


def _parent_ctx(request: web.Request) -> tuple | None:
    """Incoming trace context from the router hop (ISSUE 10 satellite):
    the router forwards ``X-VDT-Trace-Id: <trace_id>-<span_id>`` so this
    replica's spans parent under the router's root span and the whole
    request shares one trace id across processes."""
    header = request.headers.get(TRACE_HEADER)
    if not header:
        return None
    trace_id, _, span_id = header.partition("-")
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    return (trace_id, span_id)


@web.middleware
async def trace_middleware(request: web.Request, handler):
    """Root span per API request (tracing.py).  The trace id is echoed
    in the X-VDT-Trace-Id response header; handlers pick the context up
    from ``request['trace_ctx']`` and thread it through the engine so
    queue/prefill/decode/RPC spans share the trace.  A request arriving
    from the router carries a parent context in the same header, and the
    span parents under it instead of rooting a new trace.  With tracing
    off this is one attribute read per request."""
    tracer = get_tracer()
    if not tracer.enabled or request.path in _UNTRACED:
        return await handler(request)
    parent = _parent_ctx(request)
    with tracer.span(
        "api.request",
        parent=parent,
        trace_root=parent is None,
        method=request.method,
        path=request.path,
    ) as span:
        request["trace_ctx"] = span.ctx
        response = await handler(request)
        span.set_attribute("status", response.status)
    if not response.prepared:
        # Streamed (SSE) responses set the header themselves before
        # prepare(); everything else gets it stamped here.
        response.headers[TRACE_HEADER] = span.ctx[0]
    return response


# ---- helpers ----
def _error(message: str, status: int = 400) -> web.Response:
    return web.json_response(
        ErrorResponse(message=message, code=status).model_dump(),
        status=status,
    )


def _engine_dead_response(e: EngineDeadError) -> web.Response:
    """Degraded-mode rejection: 503 (not 500 — the deployment supervisor
    is restarting the backend, the request is retryable elsewhere/later)
    with Retry-After and the structured per-host attribution."""
    body = ErrorResponse(message=str(e), code=503).model_dump()
    failure = getattr(e, "failure", None)
    if failure is not None:
        body["failure"] = failure.to_dict()
    return web.json_response(
        body,
        status=503,
        headers={"Retry-After": str(envs.VDT_RETRY_AFTER_SECONDS)},
    )


def _overloaded_response(e: EngineOverloadedError) -> web.Response:
    """Load-shed rejection: 429 + Retry-After (ISSUE 8), deliberately
    DISTINCT from the dead/recovering 503s — this backend is healthy
    but full, so a load balancer should retry it soon, not eject it."""
    body = ErrorResponse(
        message=str(e), type="overloaded_error", code=429
    ).model_dump()
    body["reason"] = getattr(e, "reason", "overloaded")
    return web.json_response(
        body,
        status=429,
        headers={"Retry-After": str(getattr(e, "retry_after", 1))},
    )


def _request_error(e: Exception) -> web.Response:
    if isinstance(e, EngineOverloadedError):
        return _overloaded_response(e)
    if isinstance(e, EngineDeadError):
        return _engine_dead_response(e)
    return _error(str(e), 400)


def _apply_deadline(request: web.Request, params) -> web.Response | None:
    """Fold the X-VDT-Deadline-Ms header into the sampling params (the
    body field wins).  Returns an error response for a malformed
    header, else None."""
    header = request.headers.get(DEADLINE_HEADER)
    if header is None or params.deadline_ms is not None:
        return None
    try:
        ms = int(header)
        if ms < 1:
            raise ValueError
    except ValueError:
        return _error(
            f"{DEADLINE_HEADER} must be a positive integer, got "
            f"{header!r}"
        )
    params.deadline_ms = ms
    return None


def _apply_slo_class(request: web.Request, req_model, params) -> None:
    """Fold the X-VDT-SLO-Class header into the sampling params.  An
    EXPLICIT body field wins (req_model.slo_class is None only when the
    body omitted it, so a client naming "default" beats the header).
    Never rejects: the class is telemetry, and engine/slo.py sanitizes
    + bounds whatever arrives."""
    header = request.headers.get(SLO_CLASS_HEADER)
    if header and req_model.slo_class is None:
        params.slo_class = header


def _apply_disagg_prefill(
    request: web.Request, params, req_model, num_prompts: int = 1
) -> None:
    """Fold the router's ``X-VDT-Disagg: prefill`` hop marker into the
    sampling params (ISSUE 15): the request runs prefill plus ONE
    sampled token, then finishes with its pages held for export.  Only
    single-choice streaming requests qualify (the router never plans a
    hand-off for anything else); everything else ignores the header."""
    if request.headers.get(DISAGG_HEADER) != "prefill":
        return
    if not req_model.stream or req_model.n != 1 or num_prompts != 1:
        return
    params.prefill_only = True
    params.max_tokens = 1


def _apply_chat_template(state: ServerState, req: ChatCompletionRequest) -> str:
    tokenizer = state.engine.tokenizer
    conversation = [
        m.model_dump(exclude_none=True) for m in req.messages
    ]
    template = req.chat_template or state.chat_template
    kwargs = req.chat_template_kwargs or {}
    if tokenizer is not None and (
        template or getattr(tokenizer, "chat_template", None)
    ):
        return tokenizer.apply_chat_template(
            conversation,
            tokenize=False,
            add_generation_prompt=req.add_generation_prompt,
            chat_template=template,
            tools=req.tools,
            **kwargs,
        )
    # No template available: a plain readable fallback.
    lines = [
        f"{m.get('role')}: {m.get('content') or ''}" for m in conversation
    ]
    lines.append("assistant:")
    return "\n".join(lines)


def _logprobs_dict(out, chat: bool) -> dict | None:
    comp = out.outputs[0]
    if comp.logprobs is None:
        return None
    if chat:
        content = []
        for tok, lp in zip(comp.token_ids, comp.logprobs):
            entry = {
                "token": str(tok),
                "logprob": lp.get(tok, 0.0),
                "top_logprobs": [
                    {"token": str(t), "logprob": v}
                    for t, v in sorted(lp.items(), key=lambda kv: -kv[1])
                ],
            }
            content.append(entry)
        return {"content": content}
    return {
        "tokens": [str(t) for t in comp.token_ids],
        "token_logprobs": [
            lp.get(t, 0.0) for t, lp in zip(comp.token_ids, comp.logprobs)
        ],
        "top_logprobs": [
            {str(t): v for t, v in lp.items()} for lp in comp.logprobs
        ],
    }


async def _collect(gen) -> RequestOutput:
    last = None
    async for out in gen:
        last = out
    return last


def _shed_response(outs: list[RequestOutput]) -> web.Response | None:
    """Map engine-side preempt-to-shed finishes to HTTP 429 on the
    non-streaming path (ISSUE 8): an admitted request the scheduler
    shed under sustained pressure IS a rejection, even though it
    carries partial output.  Streaming responses instead deliver
    finish_reason="overloaded" in the final chunk (headers are long
    gone)."""
    if any(
        out.outputs[0].finish_reason == "overloaded" for out in outs
    ):
        return _overloaded_response(
            EngineOverloadedError(
                "request shed under sustained KV pressure "
                "(preempt-to-shed); retry later",
                reason="overloaded",
                retry_after=envs.VDT_OVERLOAD_RETRY_AFTER_SECONDS,
            )
        )
    return None


# ---- route handlers ----
async def health(request: web.Request) -> web.Response:
    state: ServerState = request.app["state"]
    try:
        await state.engine.check_health()
    except EngineRecoveringError as e:
        # Third engine state: the supervisor is rebuilding in-process.
        # Still 503 (don't route new traffic here yet), but the body
        # says RECOVERING and Retry-After tracks the backoff schedule,
        # so a load balancer knows this backend is coming back.
        body = {"status": "recovering", "error": str(e)}
        failure = getattr(e, "failure", None)
        if failure is not None:
            # The originating HostFailure that triggered the recovery.
            body["failure"] = failure.to_dict()
        return web.json_response(
            body,
            status=503,
            headers={"Retry-After": str(e.retry_after)},
        )
    except EngineDeadError as e:
        body = {"status": "dead", "error": str(e)}
        failure = getattr(e, "failure", None)
        if failure is not None:
            # Per-host attribution verbatim from the control plane:
            # which host, which lifecycle phase, and the cause chain.
            body["failure"] = failure.to_dict()
        return web.json_response(
            body,
            status=503,
            headers={"Retry-After": str(envs.VDT_RETRY_AFTER_SECONDS)},
        )
    if state.engine.draining:
        # Fourth engine state (ISSUE 8): healthy but not admitting —
        # in-flight work is finishing (draining) or has been journaled
        # for hand-off (drained).  503 takes this backend out of LB
        # rotation; the body says why.
        return web.json_response(
            {"status": state.engine.drain_state_name},
            status=503,
            headers={"Retry-After": str(envs.VDT_RETRY_AFTER_SECONDS)},
        )
    # Wall-clock in the body (ISSUE 20): the router pairs it with its
    # own send/recv stamps to estimate this replica's clock offset for
    # /router/timeline correction (heartbeat-RTT style, ISSUE 4).
    body = {"status": "ok", "now": time.time()}
    if state.replica_id:
        body["replica_id"] = state.replica_id
    if state.role and state.role != "mixed":
        body["role"] = state.role
    return web.json_response(body)


async def version(request: web.Request) -> web.Response:
    return web.json_response({"version": __version__})


async def drain(request: web.Request) -> web.Response:
    """Graceful drain (ISSUE 8): stop admission (new requests 429,
    /health reports the drain state), let in-flight requests finish for
    up to ``?timeout=<seconds>`` (default VDT_DRAIN_TIMEOUT_SECONDS),
    then journal what remains to VDT_DRAIN_JOURNAL_PATH so a restarted
    engine — or another replica — replays it with zero lost admitted
    work.  The SIGTERM handler calls the same path."""
    state: ServerState = request.app["state"]
    timeout = None
    raw = request.query.get("timeout")
    if raw is not None:
        try:
            timeout = float(raw)
            if timeout < 0:
                raise ValueError
        except ValueError:
            return _error(f"timeout must be a non-negative number, got {raw!r}")
    result = await state.engine.drain(timeout)
    return web.json_response(result)


async def list_models(request: web.Request) -> web.Response:
    state: ServerState = request.app["state"]
    card = ModelCard(id=state.model_name, max_model_len=state.max_model_len)
    return web.json_response(ModelList(data=[card]).model_dump())


async def tokenize(request: web.Request) -> web.Response:
    state: ServerState = request.app["state"]
    req = TokenizeRequest(**await request.json())
    tokenizer = state.engine.tokenizer
    if tokenizer is None:
        return _error("tokenizer unavailable", 400)
    ids = tokenizer.encode(
        req.prompt, add_special_tokens=req.add_special_tokens
    )
    return web.json_response(
        TokenizeResponse(
            tokens=ids, count=len(ids), max_model_len=state.max_model_len
        ).model_dump()
    )


async def detokenize(request: web.Request) -> web.Response:
    state: ServerState = request.app["state"]
    req = DetokenizeRequest(**await request.json())
    tokenizer = state.engine.tokenizer
    if tokenizer is None:
        return _error("tokenizer unavailable", 400)
    return web.json_response(
        DetokenizeResponse(prompt=tokenizer.decode(req.tokens)).model_dump()
    )


async def chat_completions(request: web.Request) -> web.Response:
    state: ServerState = request.app["state"]
    try:
        req = ChatCompletionRequest(**await request.json())
    except Exception as e:  # noqa: BLE001
        return _error(f"invalid request: {e}")
    request_id = f"chatcmpl-{next(state.request_counter)}"

    prompt = _apply_chat_template(state, req)
    tokenizer = state.engine.tokenizer
    prompt_ids = tokenizer.encode(prompt) if tokenizer else None
    if prompt_ids is not None and len(prompt_ids) >= state.max_model_len:
        return _error(
            f"prompt has {len(prompt_ids)} tokens, exceeding "
            f"max_model_len {state.max_model_len}"
        )
    default_max = state.max_model_len - (
        len(prompt_ids) if prompt_ids else 0
    ) - 1
    try:
        params = req.to_sampling_params(default_max, is_chat=True)
    except ValueError as e:
        return _error(str(e))
    err = _apply_deadline(request, params)
    if err is not None:
        return err
    _apply_slo_class(request, req, params)
    _apply_disagg_prefill(request, params, req)

    # Admission pre-check (no reservation): overload rejects become
    # proper 429s HERE, before any SSE stream opens; generate() runs
    # the authoritative reserving check per choice.
    try:
        state.engine.check_admission(
            num_requests=req.n,
            est_tokens=(len(prompt_ids) if prompt_ids else 0) * req.n,
            prompt_token_ids=prompt_ids,
            slo_class=params.slo_class,
        )
    except EngineOverloadedError as e:
        return _overloaded_response(e)

    if req.stream:
        return await _stream_chat(request, state, req, request_id, prompt_ids, prompt, params)

    try:
        outs = await asyncio.gather(
            *(
                _collect(
                    state.engine.generate(
                        f"{request_id}-{i}",
                        prompt=None if prompt_ids else prompt,
                        prompt_token_ids=prompt_ids,
                        sampling_params=params.clone(),
                        trace_ctx=request.get("trace_ctx"),
                    )
                )
                for i in range(req.n)
            )
        )
    except (EngineOverloadedError, EngineDeadError, ValueError) as e:
        return _request_error(e)
    shed = _shed_response(outs)
    if shed is not None:
        return shed

    choices = []
    usage = UsageInfo()
    for i, out in enumerate(outs):
        comp = out.outputs[0]
        content, tool_calls = comp.text, []
        if state.tool_call_parser and (req.tools or state.enable_auto_tool_choice):
            parser = ToolParserManager.get(state.tool_call_parser)
            content, tool_calls = parser.extract(comp.text)
        finish = comp.finish_reason
        if tool_calls:
            finish = "tool_calls"
        choices.append(
            ChatChoice(
                index=i,
                message=ChatResponseMessage(
                    content=content,
                    tool_calls=[ToolCall(**tc) for tc in tool_calls] or None,
                ),
                logprobs=_logprobs_dict(out, chat=True),
                finish_reason=finish,
            )
        )
        usage.prompt_tokens += len(out.prompt_token_ids)
        usage.completion_tokens += len(comp.token_ids)
    usage.total_tokens = usage.prompt_tokens + usage.completion_tokens
    resp = ChatCompletionResponse(
        id=request_id, model=state.model_name, choices=choices, usage=usage
    )
    return web.json_response(resp.model_dump(exclude_none=True))


async def _stream_chat(
    request, state, req, request_id, prompt_ids, prompt, params
) -> web.StreamResponse:
    headers = {
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
        "Connection": "keep-alive",
    }
    trace_ctx = request.get("trace_ctx")
    if trace_ctx is not None:
        headers[TRACE_HEADER] = trace_ctx[0]
    if state.replica_id:
        headers[REPLICA_HEADER] = state.replica_id
    response = web.StreamResponse(headers=headers)
    await response.prepare(request)

    async def send(obj) -> None:
        payload = obj if isinstance(obj, str) else json.dumps(
            obj.model_dump(exclude_none=True)
        )
        await response.write(f"data: {payload}\n\n".encode())

    # Router hop (ISSUE 10): chunks carry vdt_token_ids metadata so the
    # router can journal emitted tokens for live migration.
    router_meta = request.headers.get(ROUTER_HEADER) == "1"
    include_usage = bool(
        (req.stream_options or {}).get("include_usage", False)
    )
    usage = UsageInfo()
    # Streaming tool-call parsing (the reference's flagship COMMAND is
    # an agent workload: --enable-auto-tool-choice --tool-call-parser
    # qwen3_coder, .env.server:11): tool-call fragments stream in SSE
    # chunks as the text arrives, not after the request finishes.
    use_tools = bool(
        state.tool_call_parser
        and (req.tools or state.enable_auto_tool_choice)
    )

    async def stream_one(i: int) -> None:
        first = True
        sent = 0
        sent_toks = 0
        finish = None
        stream_parser = (
            ToolParserManager.get(state.tool_call_parser).streaming()
            if use_tools
            else None
        )
        sent_tool_deltas = False

        async def emit(
            delta: ChatDelta, finish_reason=None, meta: dict | None = None
        ) -> None:
            payload = ChatCompletionStreamResponse(
                id=request_id,
                model=state.model_name,
                choices=[
                    ChatStreamChoice(
                        index=i, delta=delta,
                        finish_reason=finish_reason,
                    )
                ],
            ).model_dump(exclude_none=True)
            if meta:
                payload["choices"][0].update(meta)
            await send(json.dumps(payload))

        async for out in state.engine.generate(
            f"{request_id}-{i}",
            prompt=None if prompt_ids else prompt,
            prompt_token_ids=prompt_ids,
            sampling_params=params.clone(),
            trace_ctx=trace_ctx,
        ):
            comp = out.outputs[0]
            delta_text = comp.text[sent:]
            sent = len(comp.text)
            new_ids = list(comp.token_ids[sent_toks:])
            sent_toks = len(comp.token_ids)
            finish = comp.finish_reason
            tool_deltas: list[dict] = []
            if stream_parser is not None:
                delta_text, tool_deltas = stream_parser.push(delta_text)
                if comp.finished:
                    tail_text, tail_tools = stream_parser.finish()
                    delta_text += tail_text
                    tool_deltas += tail_tools
                sent_tool_deltas |= bool(tool_deltas)
            if comp.finished and sent_tool_deltas:
                finish = "tool_calls"
            if first or delta_text or tool_deltas or comp.finished or (
                router_meta and new_ids
            ):
                delta = ChatDelta(
                    role="assistant" if first else None,
                    content=delta_text or ("" if first else None),
                    tool_calls=tool_deltas or None,
                )
                meta = None
                if router_meta:
                    meta = {"vdt_token_ids": new_ids}
                    if first:
                        meta["vdt_prompt_token_ids"] = list(
                            out.prompt_token_ids
                        )
                    if params.prefill_only:
                        # The export handle the router drives
                        # /internal/kv/export with (ISSUE 15).
                        meta["vdt_kv_handle"] = f"{request_id}-{i}"
                first = False
                await emit(
                    delta, finish if comp.finished else None, meta
                )
            if comp.finished:
                usage.prompt_tokens += len(out.prompt_token_ids)
                usage.completion_tokens += len(comp.token_ids)

    try:
        await asyncio.gather(*(stream_one(i) for i in range(req.n)))
        if include_usage:
            usage.total_tokens = usage.prompt_tokens + usage.completion_tokens
            await send(
                ChatCompletionStreamResponse(
                    id=request_id,
                    model=state.model_name,
                    choices=[],
                    usage=usage,
                )
            )
        await send("[DONE]")
    except EngineOverloadedError as e:
        # Mid-stream shed/drain: headers are long sent, so the reject
        # rides the stream as a typed error frame with the 429 code.
        await send(
            json.dumps(
                {"error": str(e), "code": 429, "reason": e.reason}
            )
        )
    except (EngineDeadError, ValueError) as e:
        # The code tells a fronting router whether this is migratable
        # (503: the backend died, replay elsewhere) or final (400).
        await send(
            json.dumps(
                {
                    "error": str(e),
                    "code": 503 if isinstance(e, EngineDeadError) else 400,
                }
            )
        )
    except (ConnectionResetError, asyncio.CancelledError):
        logger.info("client disconnected from %s", request_id)
    await response.write_eof()
    return response


async def completions(request: web.Request) -> web.Response:
    state: ServerState = request.app["state"]
    try:
        req = CompletionRequest(**await request.json())
    except Exception as e:  # noqa: BLE001
        return _error(f"invalid request: {e}")
    request_id = f"cmpl-{next(state.request_counter)}"
    tokenizer = state.engine.tokenizer

    # Normalize prompt forms: str | [str] | [int] | [[int]].
    prompts: list[tuple[str | None, list[int] | None]] = []
    p = req.prompt
    if isinstance(p, str):
        prompts = [(p, None)]
    elif isinstance(p, list) and p and isinstance(p[0], int):
        prompts = [(None, p)]
    elif isinstance(p, list) and p and isinstance(p[0], str):
        prompts = [(s, None) for s in p]
    elif isinstance(p, list) and p and isinstance(p[0], list):
        prompts = [(None, ids) for ids in p]
    else:
        return _error("invalid prompt")

    resolved: list[tuple[str | None, list[int]]] = []
    for text, ids in prompts:
        if ids is None:
            if tokenizer is None:
                return _error("tokenizer unavailable for text prompts")
            ids = tokenizer.encode(text)
        resolved.append((text, ids))

    longest = max(len(ids) for _, ids in resolved)
    if longest >= state.max_model_len:
        return _error(
            f"prompt has {longest} tokens, exceeding max_model_len "
            f"{state.max_model_len}"
        )
    default_max = state.max_model_len - longest - 1
    try:
        params = req.to_sampling_params(default_max, is_chat=False)
    except ValueError as e:
        return _error(str(e))
    err = _apply_deadline(request, params)
    if err is not None:
        return err
    _apply_slo_class(request, req, params)
    _apply_disagg_prefill(request, params, req, num_prompts=len(resolved))

    try:
        state.engine.check_admission(
            num_requests=len(resolved) * req.n,
            est_tokens=sum(len(ids) for _, ids in resolved) * req.n,
            prompt_token_ids=resolved[0][1],
            slo_class=params.slo_class,
        )
    except EngineOverloadedError as e:
        return _overloaded_response(e)

    if req.stream:
        return await _stream_completion(
            request, state, req, request_id, resolved, params
        )

    gens = []
    for pi, (text, ids) in enumerate(resolved):
        for i in range(req.n):
            gens.append(
                _collect(
                    state.engine.generate(
                        f"{request_id}-{pi}-{i}",
                        prompt=text,
                        prompt_token_ids=ids,
                        sampling_params=params.clone(),
                        trace_ctx=request.get("trace_ctx"),
                    )
                )
            )
    try:
        outs = await asyncio.gather(*gens)
    except (EngineOverloadedError, EngineDeadError, ValueError) as e:
        return _request_error(e)
    shed = _shed_response(outs)
    if shed is not None:
        return shed

    choices = []
    usage = UsageInfo()
    score_cache: dict[tuple, list] = {}  # n choices share one prompt
    for idx, out in enumerate(outs):
        comp = out.outputs[0]
        text = comp.text
        lp_dict = _logprobs_dict(out, chat=False)
        if req.echo:
            prefix = out.prompt or (
                tokenizer.decode(out.prompt_token_ids) if tokenizer else ""
            )
            text = prefix + text
            if lp_dict is not None:
                # Echoed prompts report prompt logprobs too (vLLM's
                # prompt_logprobs surface): a teacher-forced scoring
                # pass off the hot path (model_runner.score).
                key = tuple(out.prompt_token_ids)
                try:
                    if key not in score_cache:
                        score_cache[key] = await state.engine.score(
                            out.prompt_token_ids
                        )
                    prompt_lps = score_cache[key]
                except EngineDeadError as e:
                    return _engine_dead_response(e)
                lp_dict = {
                    "tokens": [str(t) for t in out.prompt_token_ids]
                    + lp_dict["tokens"],
                    "token_logprobs": prompt_lps
                    + lp_dict["token_logprobs"],
                    "top_logprobs": [None] * len(out.prompt_token_ids)
                    + lp_dict["top_logprobs"],
                }
        choices.append(
            CompletionChoice(
                index=idx,
                text=text,
                logprobs=lp_dict,
                finish_reason=comp.finish_reason,
            )
        )
        usage.prompt_tokens += len(out.prompt_token_ids)
        usage.completion_tokens += len(comp.token_ids)
    usage.total_tokens = usage.prompt_tokens + usage.completion_tokens
    resp = CompletionResponse(
        id=request_id, model=state.model_name, choices=choices, usage=usage
    )
    return web.json_response(resp.model_dump(exclude_none=True))


async def _stream_completion(
    request, state, req, request_id, resolved, params
) -> web.StreamResponse:
    headers = {
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
    }
    trace_ctx = request.get("trace_ctx")
    if trace_ctx is not None:
        headers[TRACE_HEADER] = trace_ctx[0]
    if state.replica_id:
        headers[REPLICA_HEADER] = state.replica_id
    response = web.StreamResponse(headers=headers)
    await response.prepare(request)

    async def send_json(payload: str) -> None:
        await response.write(f"data: {payload}\n\n".encode())

    no_tokenizer = state.engine.tokenizer is None
    router_meta = request.headers.get(ROUTER_HEADER) == "1"
    include_usage = bool(
        (req.stream_options or {}).get("include_usage", False)
    )
    usage = UsageInfo()

    async def stream_one(choice_idx: int, text, ids) -> None:
        sent = 0
        sent_toks = 0
        first = True
        async for out in state.engine.generate(
            f"{request_id}-{choice_idx}",
            prompt=text,
            prompt_token_ids=ids,
            sampling_params=params.clone(),
            trace_ctx=trace_ctx,
        ):
            comp = out.outputs[0]
            delta = comp.text[sent:]
            sent = len(comp.text)
            new_ids = list(comp.token_ids[sent_toks:])
            sent_toks = len(comp.token_ids)
            if comp.finished:
                usage.prompt_tokens += len(out.prompt_token_ids)
                usage.completion_tokens += len(comp.token_ids)
            # Without a tokenizer (dummy-weight serving/benches) there is
            # no text to delta — stream empty chunks on token arrival so
            # SSE timing still reflects token delivery.
            if delta or comp.finished or (
                new_ids and (no_tokenizer or router_meta)
            ):
                chunk = CompletionResponse(
                    id=request_id,
                    model=state.model_name,
                    choices=[
                        CompletionChoice(
                            index=choice_idx,
                            text=delta,
                            finish_reason=(
                                comp.finish_reason if comp.finished else None
                            ),
                        )
                    ],
                ).model_dump(exclude_none=True)
                if router_meta:
                    chunk["choices"][0]["vdt_token_ids"] = new_ids
                    if first:
                        chunk["choices"][0]["vdt_prompt_token_ids"] = list(
                            out.prompt_token_ids
                        )
                    if params.prefill_only:
                        # The export handle the router drives
                        # /internal/kv/export with (ISSUE 15).
                        chunk["choices"][0]["vdt_kv_handle"] = (
                            f"{request_id}-{choice_idx}"
                        )
                first = False
                await send_json(json.dumps(chunk))

    try:
        tasks = []
        idx = 0
        for text, ids in resolved:
            for _ in range(req.n):
                tasks.append(stream_one(idx, text, ids))
                idx += 1
        await asyncio.gather(*tasks)
        if include_usage:
            usage.total_tokens = (
                usage.prompt_tokens + usage.completion_tokens
            )
            final = CompletionResponse(
                id=request_id,
                model=state.model_name,
                choices=[],
                usage=usage,
            )
            await send_json(json.dumps(final.model_dump(exclude_none=True)))
        await send_json("[DONE]")
    except EngineOverloadedError as e:
        await send_json(
            json.dumps(
                {"error": str(e), "code": 429, "reason": e.reason}
            )
        )
    except (EngineDeadError, ValueError) as e:
        # 503 = backend death (a router live-migrates), 400 = final.
        await send_json(
            json.dumps(
                {
                    "error": str(e),
                    "code": 503 if isinstance(e, EngineDeadError) else 400,
                }
            )
        )
    except (ConnectionResetError, asyncio.CancelledError):
        logger.info("client disconnected from %s", request_id)
    await response.write_eof()
    return response


async def metrics(request: web.Request) -> web.Response:
    """Engine-loop Prometheus instruments (TTFT/ITL/throughput/queues —
    the reference serves vLLM's via build_app, launch.py:429-432).
    Each scrape also pulls the worker-side XLA/HBM telemetry snapshot
    (ISSUE 12) so compile counters and memory gauges stay current in
    steady state — best-effort: a dead/recovering engine just serves
    the previous values."""
    state: ServerState = request.app["state"]
    try:
        await state.engine.refresh_device_telemetry()
    except Exception as e:  # noqa: BLE001 — scrape must answer anyway
        logger.debug("device-telemetry refresh failed: %s", e)
    return web.Response(
        body=state.engine.metrics.render(), content_type="text/plain"
    )


async def slo(request: web.Request) -> web.Response:
    """Per-class SLO/goodput view (ISSUE 12, engine/slo.py): attainment
    counters, mergeable log-bucket TTFT/ITL histograms, and the bounded
    ring of raw per-request timelines.  The router's /router/slo merges
    N replicas' views associatively into the fleet picture; the
    ``timelines`` ring is what the merge is bit-recomputable from
    (``?timelines=0`` omits it for cheap scrapes)."""
    state: ServerState = request.app["state"]
    include = request.query.get("timelines", "1") not in ("0", "false")
    snap = state.engine.metrics.slo_snapshot(include_timelines=include)
    if snap is None:
        return _error(
            "SLO accounting disabled (--disable-log-stats)", 404
        )
    if state.replica_id:
        snap["replica_id"] = state.replica_id
    return web.json_response(snap)


async def debug_flightrecorder(request: web.Request) -> web.Response:
    """The engine flight recorder's bounded per-step ring (ISSUE 12),
    on demand.  ``?dump=1`` also writes the JSON artifact (same format
    as the automatic HostFailure/recovery/drain dumps) and returns its
    path."""
    state: ServerState = request.app["state"]
    recorder = state.engine.engine.flight_recorder
    if not recorder.enabled:
        return _error(
            "flight recorder disabled (VDT_FLIGHT_RECORDER_SIZE=0)", 404
        )
    body = recorder.snapshot()
    if request.query.get("dump") in ("1", "true"):
        body["path"] = recorder.dump("on_demand")
    return web.json_response(body)


async def debug_events(request: web.Request) -> web.Response:
    """This replica's slice of the unified event timeline (ISSUE 20):
    the engine's bounded SentinelLog (flight-recorder dumps, recovery
    transitions, QoS sheds, KV hand-off/restore outcomes), each event
    carrying both monotonic and wall stamps so the router can merge it
    fleet-wide at /router/timeline with clock-offset correction."""
    state: ServerState = request.app["state"]
    log = state.engine.engine.metrics.events
    if not log.enabled:
        return _error(
            "event timeline disabled (VDT_SENTINEL_EVENTS_SIZE=0)", 404
        )
    body = {
        "source": log.source,
        "now_wall": time.time(),
        "now_mono": time.monotonic(),
        "events": log.snapshot(),
    }
    if state.replica_id:
        body["replica_id"] = state.replica_id
    return web.json_response(body)


async def debug_profile(request: web.Request) -> web.Response:
    """Gated server-side jax.profiler capture (ISSUE 12):
    ``POST /debug/profile?seconds=N`` records a trace into the
    configured profile directory (--profile-dir / VDT_PROFILE_DIR) and
    returns the artifact path.  404 while unconfigured — profiling is
    an operator opt-in, like /debug/traces.  One capture at a time."""
    state: ServerState = request.app["state"]
    profile_dir = (
        state.engine.config.observability_config.profile_dir
    )
    if not profile_dir:
        return _error(
            "profiling disabled: start with --profile-dir (or "
            "VDT_PROFILE_DIR) to enable POST /debug/profile",
            404,
        )
    try:
        seconds = float(request.query.get("seconds", "1"))
    except ValueError:
        return _error("seconds must be a number")
    if not 0 < seconds <= 120:
        return _error("seconds must be in (0, 120]")
    if request.app.get("_profiling"):
        return _error("a profile capture is already running", 409)
    path = os.path.join(
        profile_dir, f"profile-{int(time.time() * 1000)}"
    )

    def capture() -> None:
        # Runs on an executor thread: the sleep must not block the
        # event loop for the capture window.
        import jax

        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()

    request.app["_profiling"] = True
    try:
        await asyncio.get_running_loop().run_in_executor(None, capture)
    except Exception as e:  # noqa: BLE001 — surface, don't 500-crash
        return _error(f"profile capture failed: {e}", 503)
    finally:
        request.app["_profiling"] = False
    return web.json_response({"path": path, "seconds": seconds})


async def debug_traces(request: web.Request) -> web.Response:
    """Recent completed request traces (tracing.py ring buffer).

    ``?format=chrome`` returns Chrome trace-event JSON that loads
    directly in Perfetto (https://ui.perfetto.dev) or chrome://tracing;
    the default JSON form is what tools/trace_summary.py consumes.
    ``?trace_id=<id>`` fetches one trace; ``?limit=N`` bounds the dump.
    404 with a documented body while tracing is disabled."""
    tracer = get_tracer()
    if not tracer.enabled:
        return _error(
            "tracing disabled: start with --enable-tracing or "
            "VDT_TRACING=1 to populate /debug/traces",
            404,
        )
    trace_id = request.query.get("trace_id")
    if trace_id:
        trace = tracer.get_trace(trace_id)
        if trace is None:
            return _error(f"trace {trace_id!r} not found", 404)
        return web.json_response({"traces": [trace]})
    try:
        limit = int(request.query.get("limit", "0")) or None
    except ValueError:
        return _error("limit must be an integer")
    if limit is not None and limit < 0:
        return _error("limit must be a non-negative integer")
    if request.query.get("format") == "chrome":
        return web.json_response(tracer.to_chrome(limit))
    return web.json_response({"traces": tracer.snapshot(limit)})


async def embeddings(request: web.Request) -> web.Response:
    """Pooled (mean, L2-normalized) final-hidden-state embeddings — the
    causal-LM pooling path the reference inherits via vLLM's app
    (launch.py:429; SURVEY.md §2.3 build_app row)."""
    state: ServerState = request.app["state"]
    try:
        req = EmbeddingRequest(**await request.json())
    except Exception as e:  # noqa: BLE001
        return _error(f"invalid request: {e}")
    if req.encoding_format not in ("float", "base64"):
        return _error(
            f"unsupported encoding_format {req.encoding_format!r}"
        )
    tokenizer = state.engine.tokenizer

    try:
        p = req.input
        if isinstance(p, str):
            p = [p]
        if isinstance(p, list) and p and isinstance(p[0], int):
            items = [[int(t) for t in p]]  # single token list
        elif isinstance(p, list) and p and isinstance(p[0], str):
            if tokenizer is None:
                return _error("tokenizer unavailable for text input")
            items = [tokenizer.encode(str(s)) for s in p]
        elif isinstance(p, list) and p and isinstance(p[0], list):
            items = [[int(t) for t in ids] for ids in p]
        else:
            return _error("invalid input")
    except (TypeError, ValueError) as e:
        return _error(f"invalid input: {e}")
    if any(not ids for ids in items):
        return _error("input must contain at least one token")
    longest = max(len(ids) for ids in items)
    # `>` not `>=`: embeddings generate nothing, so no headroom needed.
    if longest > state.max_model_len:
        return _error(
            f"input has {longest} tokens, exceeding max_model_len "
            f"{state.max_model_len}"
        )
    try:
        vectors = await asyncio.gather(
            *(state.engine.embed(ids) for ids in items)
        )
    except EngineDeadError as e:
        return _engine_dead_response(e)
    if req.encoding_format == "base64":
        import base64
        import struct

        vectors = [
            base64.b64encode(
                struct.pack(f"<{len(v)}f", *v)
            ).decode("ascii")
            for v in vectors
        ]
    usage = UsageInfo(prompt_tokens=sum(len(i) for i in items))
    usage.total_tokens = usage.prompt_tokens
    resp = EmbeddingResponse(
        model=state.model_name,
        data=[
            EmbeddingData(index=i, embedding=v)
            for i, v in enumerate(vectors)
        ],
        usage=usage,
    )
    return web.json_response(resp.model_dump())


async def tokenizer_info(request: web.Request) -> web.Response:
    """Tokenizer metadata (the reference registers vLLM's
    maybe_register_tokenizer_info_endpoint, launch.py:34, 428)."""
    state: ServerState = request.app["state"]
    tok = state.engine.tokenizer
    if tok is None:
        return _error("tokenizer unavailable")
    info = {
        "tokenizer_class": type(tok).__name__,
        "vocab_size": getattr(tok, "vocab_size", None),
        "model_max_length": getattr(tok, "model_max_length", None),
        "bos_token": getattr(tok, "bos_token", None),
        "eos_token": getattr(tok, "eos_token", None),
        "pad_token": getattr(tok, "pad_token", None),
        "chat_template": state.chat_template
        or getattr(tok, "chat_template", None),
    }
    return web.json_response(info)


async def internal_resume(request: web.Request) -> web.Response:
    """Live-migration hand-off target (ISSUE 10, router/).  The router
    posts one journaled in-flight request — original OpenAI body (for
    sampling-param parity with the first admission), prompt token ids,
    and the tokens already delivered to the client — and this replica
    re-admits it with the emitted tokens restored as OUTPUT tokens (the
    ``engine/supervisor.py`` JournalEntry preemption-resume semantics,
    via AsyncLLM.register_resumable), so the continuation's greedy
    tokens are bit-identical to an unmigrated run.

    The reply is an internal SSE stream, one JSON frame per output:
    cumulative ``text``, the NEW ``token_ids`` beyond the restored ones
    (the first frame also carries ``prompt_token_ids``), a final frame
    with ``finish_reason`` + ``usage``, then ``[DONE]``.  The router
    converts frames back into client-facing OpenAI chunks.  Logprobs
    are not journaled or restored — a non-issue for streams (the SSE
    chunk format never carries logprobs) and non-streaming requests
    are resubmitted whole, regenerating them."""
    from vllm_distributed_tpu.engine.supervisor import JournalEntry

    state: ServerState = request.app["state"]
    try:
        d = await request.json()
        kind = d.get("kind", "completions")
        rid = str(d["request_id"])
        emitted = [int(t) for t in d.get("emitted_token_ids") or ()]
        body = d.get("body") or {}
    except Exception as e:  # noqa: BLE001
        return _error(f"invalid resume payload: {e}")
    engine = state.engine
    if engine.draining:
        # A draining replica is leaving rotation: accepting a migration
        # here would just migrate it again moments later.
        return web.json_response(
            ErrorResponse(
                message="replica is draining; not accepting migrations",
                code=503,
            ).model_dump(),
            status=503,
            headers={"Retry-After": str(envs.VDT_RETRY_AFTER_SECONDS)},
        )
    try:
        if kind == "chat":
            req = ChatCompletionRequest(**body)
        else:
            req = CompletionRequest(**body)
    except Exception as e:  # noqa: BLE001
        return _error(f"invalid resume body: {e}")
    prompt_ids = d.get("prompt_token_ids")
    if prompt_ids is None:
        # No ids learned from the dead replica's metadata: re-derive
        # them locally (deterministic given the shared model/template).
        tokenizer = engine.tokenizer
        prompt_text = d.get("prompt")
        if kind == "chat":
            prompt_text = _apply_chat_template(state, req)
        if prompt_text is None or tokenizer is None:
            return _error(
                "resume payload carries neither prompt_token_ids nor a "
                "tokenizable prompt"
            )
        prompt_ids = tokenizer.encode(prompt_text)
    prompt_ids = [int(t) for t in prompt_ids]
    if len(prompt_ids) >= state.max_model_len:
        return _error(
            f"prompt has {len(prompt_ids)} tokens, exceeding "
            f"max_model_len {state.max_model_len}"
        )
    default_max = state.max_model_len - len(prompt_ids) - 1
    try:
        params = req.to_sampling_params(default_max, kind == "chat")
    except ValueError as e:
        return _error(str(e))
    err = _apply_deadline(request, params)
    if err is not None:
        return err
    _apply_slo_class(request, req, params)
    # Migrated requests keep their QoS standing (ISSUE 16): the router
    # journals the original class and sends it top-level, covering the
    # case where the client set it via header (not in the body we
    # replay) — the destination replica must bill the same bucket.
    # Precedence mirrors _apply_slo_class: an explicit body field wins,
    # then a header, then the journaled class.  (params.slo_class is
    # already coerced to "default" by to_sampling_params, so the guard
    # must look at the REQUEST model's field, which is None only when
    # the body omitted it.)
    resumed_class = d.get("slo_class")
    if (
        resumed_class
        and req.slo_class is None
        and not request.headers.get(SLO_CLASS_HEADER)
    ):
        params.slo_class = str(resumed_class)
    # Idempotent replay (ISSUE 17 satellite): a router that crashed
    # mid-hand-off replays the same journaled request id, and the
    # second POST must win cleanly.  Cancel the stale handler and wait
    # for its teardown — its generate() finally aborts the engine-side
    # request through the FIFO intake, so the abort is ordered BEFORE
    # the ("resume", ...) our fresh generate() enqueues below — then
    # belt-and-braces abort in case the stale handler died outside its
    # generate loop and never reached that finally.
    prior = state.resume_takeovers.get(rid)
    if prior is not None and prior is not asyncio.current_task():
        prior.cancel()
        try:
            await asyncio.wait_for(
                asyncio.gather(prior, return_exceptions=True), timeout=5
            )
        except asyncio.TimeoutError:
            return _error(
                f"stale resume handler for {rid} did not exit", 503
            )
        await engine.abort(rid)
        # Fence: the old engine-side request keeps stepping until the
        # abort is consumed, and its outputs would land in OUR queue
        # (same id) once we register below — duplicating tokens in the
        # replayed stream.  The barrier resolves only after the abort
        # applied and every stale output dispatch has run (and dropped,
        # nothing being registered under the id right now).
        try:
            await asyncio.wait_for(engine.intake_barrier(), timeout=5)
        except asyncio.TimeoutError:
            return _error(
                f"engine did not quiesce {rid} for takeover", 503
            )
    state.resume_takeovers[rid] = asyncio.current_task()
    engine.register_resumable(
        JournalEntry(
            request_id=rid,
            prompt=None,
            prompt_token_ids=prompt_ids,
            sampling_params=params,
            emitted_token_ids=emitted,
            trace_ctx=request.get("trace_ctx"),
        )
    )

    headers = {
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
    }
    trace_ctx = request.get("trace_ctx")
    if trace_ctx is not None:
        headers[TRACE_HEADER] = trace_ctx[0]
    if state.replica_id:
        headers[REPLICA_HEADER] = state.replica_id
    response = web.StreamResponse(headers=headers)
    await response.prepare(request)

    async def send_frame(obj: dict) -> None:
        await response.write(f"data: {json.dumps(obj)}\n\n".encode())

    sent_toks = len(emitted)
    first = True
    try:
        async for out in engine.generate(rid, trace_ctx=trace_ctx):
            comp = out.outputs[0]
            new_ids = list(comp.token_ids[sent_toks:])
            sent_toks = len(comp.token_ids)
            if not (new_ids or comp.finished or first):
                continue
            frame: dict = {
                "text": comp.text,
                "token_ids": new_ids,
                "finish_reason": (
                    comp.finish_reason if comp.finished else None
                ),
            }
            if first:
                frame["prompt_token_ids"] = list(out.prompt_token_ids)
                first = False
            if comp.finished:
                frame["usage"] = {
                    "prompt_tokens": len(out.prompt_token_ids),
                    "completion_tokens": len(comp.token_ids),
                }
            await send_frame(frame)
        await response.write(b"data: [DONE]\n\n")
    except EngineOverloadedError as e:
        await send_frame(
            {"error": str(e), "code": 429, "reason": e.reason}
        )
    except (EngineDeadError, ValueError) as e:
        await send_frame({"error": str(e), "code": 503})
    except (ConnectionResetError, asyncio.CancelledError):
        logger.info("router disconnected from resumed %s", rid)
    finally:
        # Only drop the registration if it is still ours — a takeover
        # that cancelled this handler has already installed itself.
        if state.resume_takeovers.get(rid) is asyncio.current_task():
            state.resume_takeovers.pop(rid, None)
    await response.write_eof()
    return response


def _kv_transfer_error(e: Exception) -> web.Response | None:
    """Map typed hand-off failures to responses the router treats as
    'abort and fall back to recompute-resume' (ISSUE 15)."""
    from vllm_distributed_tpu.engine.kv_transfer import KVTransferError

    if isinstance(e, KVTransferError):
        return _error(str(e), 409)
    if isinstance(e, EngineDeadError):
        return _engine_dead_response(e)
    return None


async def internal_kv_export(request: web.Request) -> web.Response:
    """One per-layer chunk of a held prefill's KV pages (ISSUE 15).
    Body: ``{"handle", "layer_start", "layer_count"}``; the handle is
    the ``vdt_kv_handle`` the prefill-only stream carried.  The reply
    carries base64 layer payloads with sha256 checksums plus the chain
    metadata (token ids, page/layer counts) the decode side needs."""
    import base64

    state: ServerState = request.app["state"]
    try:
        d = await request.json()
        handle = str(d["handle"])
        layer_start = int(d.get("layer_start", 0))
        layer_count = int(d.get("layer_count", 1))
    except Exception as e:  # noqa: BLE001
        return _error(f"invalid export payload: {e}")
    try:
        out = await state.engine.kv_export(handle, layer_start, layer_count)
    except Exception as e:  # noqa: BLE001 — typed mapping below; anything else is a 500-worthy bug
        resp = _kv_transfer_error(e)
        if resp is not None:
            return resp
        raise
    for layer in out.get("layers") or ():
        layer["data"] = base64.b64encode(layer["data"]).decode("ascii")
    return web.json_response(out)


async def internal_kv_release(request: web.Request) -> web.Response:
    """Release a prefill export hold's pages (hand-off finished or
    abandoned).  Idempotent — the TTL sweep covers a router that never
    calls this."""
    state: ServerState = request.app["state"]
    try:
        d = await request.json()
        handle = str(d["handle"])
    except Exception as e:  # noqa: BLE001
        return _error(f"invalid release payload: {e}")
    try:
        released = await state.engine.kv_release(handle)
    except EngineDeadError as e:
        return _engine_dead_response(e)
    return web.json_response({"released": bool(released)})


async def internal_kv(request: web.Request) -> web.Response:
    """KV-page import surface of the decode replica (ISSUE 15): the
    router streams a prefill replica's exported pages here in per-layer
    chunks, then commits, and the next ``/internal/resume`` admission
    attaches them as computed through the PR 14 plan/attach path.

    Frames (one POST each):
    - ``{"op": "begin", "prompt_token_ids": [...]}`` →
      ``{"transfer_id", "num_pages"}`` (transfer_id null = nothing
      importable here; skip to resume, recompute is always correct)
    - ``{"op": "chunk", "transfer_id", "layers": [{index, num_layers,
      shape?, data (base64), checksum}, ...]}``
    - ``{"op": "commit", "transfer_id"}`` → ``{"adopted_tokens"}``
    - ``{"op": "abort", "transfer_id"}``

    A ``begin`` may carry ``resume_from`` (ISSUE 19): the router lost a
    chunk round-trip and asks for the still-live reservation plus its
    ``received`` layer indices, then re-pulls only the missing ones.

    A checksum mismatch or incomplete transfer answers 409 and the
    reserved pages are freed — garbage KV can never be indexed."""
    import base64

    state: ServerState = request.app["state"]
    engine = state.engine
    max_frame = envs.VDT_KV_MAX_FRAME_BYTES
    if max_frame > 0 and (request.content_length or 0) > max_frame:
        # Typed bound checked against Content-Length BEFORE buffering
        # the body: an oversized (or hostile) frame costs one header
        # read, not VDT_KV_MAX_FRAME_BYTES of router-side memory.
        return web.json_response(
            ErrorResponse(
                message=(
                    f"kv frame of {request.content_length} bytes "
                    f"exceeds VDT_KV_MAX_FRAME_BYTES={max_frame}"
                ),
                code=413,
            ).model_dump(),
            status=413,
        )
    try:
        d = await request.json()
        op = str(d.get("op") or "")
    except Exception as e:  # noqa: BLE001
        return _error(f"invalid kv frame: {e}")
    try:
        if op == "begin":
            if engine.draining:
                # A draining replica is leaving rotation: importing KV
                # it will never decode just burns the transfer.
                return web.json_response(
                    ErrorResponse(
                        message="replica is draining; not accepting "
                        "kv transfers",
                        code=503,
                    ).model_dump(),
                    status=503,
                    headers={
                        "Retry-After": str(envs.VDT_RETRY_AFTER_SECONDS)
                    },
                )
            token_ids = [int(t) for t in d.get("prompt_token_ids") or ()]
            resume_from = d.get("resume_from")
            return web.json_response(
                await engine.kv_import_begin(
                    token_ids,
                    resume_from=(
                        str(resume_from)
                        if resume_from is not None
                        else None
                    ),
                )
            )
        if op == "chunk":
            tid = str(d["transfer_id"])
            layers = []
            for layer in d.get("layers") or ():
                layers.append(
                    {
                        **layer,
                        "data": base64.b64decode(layer["data"]),
                    }
                )
            return web.json_response(
                await engine.kv_import_chunk(tid, layers)
            )
        if op == "commit":
            return web.json_response(
                await engine.kv_import_commit(str(d["transfer_id"]))
            )
        if op == "abort":
            return web.json_response(
                {
                    "aborted": bool(
                        await engine.kv_import_abort(
                            str(d["transfer_id"])
                        )
                    )
                }
            )
    except KeyError as e:
        return _error(f"kv frame missing field: {e}")
    except Exception as e:  # noqa: BLE001 — typed mapping below; anything else is a 500-worthy bug
        resp = _kv_transfer_error(e)
        if resp is not None:
            return resp
        raise
    return _error(f"unknown kv frame op {op!r}")


# ---- app assembly ----
def build_app(state: ServerState) -> web.Application:
    app = web.Application(
        client_max_size=64 * 2**20,
        middlewares=[replica_middleware, auth_middleware, trace_middleware],
    )
    app["state"] = state
    app.router.add_get("/health", health)
    app.router.add_get("/ping", health)
    app.router.add_get("/version", version)
    app.router.add_post("/drain", drain)
    app.router.add_get("/v1/models", list_models)
    app.router.add_post("/tokenize", tokenize)
    app.router.add_post("/detokenize", detokenize)
    app.router.add_get("/get_tokenizer_info", tokenizer_info)
    app.router.add_post("/v1/chat/completions", chat_completions)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/embeddings", embeddings)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/slo", slo)
    app.router.add_get("/debug/traces", debug_traces)
    app.router.add_get("/debug/flightrecorder", debug_flightrecorder)
    app.router.add_get("/debug/events", debug_events)
    app.router.add_post("/debug/profile", debug_profile)
    app.router.add_post("/internal/resume", internal_resume)
    app.router.add_post("/internal/kv", internal_kv)
    app.router.add_post("/internal/kv/export", internal_kv_export)
    app.router.add_post("/internal/kv/release", internal_kv_release)
    return app


def init_app_state(
    engine: AsyncLLM,
    *,
    served_model_name: str | None = None,
    tool_call_parser: str | None = None,
    enable_auto_tool_choice: bool = False,
    chat_template: str | None = None,
    api_key: str | None = None,
    replica_id: str | None = None,
    role: str | None = None,
) -> ServerState:
    model_config = engine.get_model_config()
    if replica_id is None:
        replica_id = envs.VDT_REPLICA_ID
    if replica_id:
        engine.metrics.record_replica_info(replica_id)
    if role is None:
        role = envs.VDT_ROUTER_ROLE
    if role not in ("prefill", "decode", "mixed"):
        raise ValueError(
            f"unknown replica role {role!r}; want prefill | decode | mixed"
        )
    return ServerState(
        engine=engine,
        model_name=served_model_name or model_config.model,
        max_model_len=model_config.max_model_len,
        tool_call_parser=tool_call_parser,
        enable_auto_tool_choice=enable_auto_tool_choice,
        chat_template=chat_template,
        api_key=api_key,
        replica_id=replica_id,
        role=role,
    )


async def serve_http(
    app: web.Application,
    host: str = "0.0.0.0",
    port: int = 8000,
    ssl_certfile: str | None = None,
    ssl_keyfile: str | None = None,
    shutdown_timeout: float | None = None,
) -> web.AppRunner:
    """Start serving; returns the runner (caller owns shutdown).
    ``shutdown_timeout`` caps how long cleanup() waits for in-flight
    requests — the router tests/chaos harness pass a tiny value so
    'kill a replica' means connections actually die mid-stream."""
    ssl_context = None
    if ssl_certfile:
        import ssl as ssl_mod

        ssl_context = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_SERVER)
        ssl_context.load_cert_chain(ssl_certfile, ssl_keyfile)
    runner_kwargs = {}
    if shutdown_timeout is not None:
        runner_kwargs["shutdown_timeout"] = shutdown_timeout
    runner = web.AppRunner(
        app,
        keepalive_timeout=envs.VDT_HTTP_TIMEOUT_KEEP_ALIVE,
        # Cancel handler tasks when the client disconnects (aiohttp
        # disables this by default since 3.9): a non-streaming
        # completion whose client hung up must not keep generating —
        # the cancelled handler's generate() iterators abort their
        # engine-side requests (ISSUE 8 satellite; the streaming path
        # already aborted via its write failing).
        handler_cancellation=True,
        **runner_kwargs,
    )
    await runner.setup()
    site = web.TCPSite(runner, host, port, ssl_context=ssl_context)
    await site.start()
    logger.info("API server listening on %s:%d", host, port)
    return runner
