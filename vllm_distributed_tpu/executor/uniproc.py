"""Single-process executor: one in-process Worker owning all local chips.

The degenerate topology (parity config 1-3: single host).  TP across the
host's chips needs no RPC at all — the mesh lives in this process and XLA
drives all chips from one Python thread, which is precisely why the
TPU-native design collapses the reference's process-per-GPU fan-out
(SURVEY.md §2.5 "no TPU analog of one process per GPU").
"""

from __future__ import annotations

import concurrent.futures
from typing import Any

from vllm_distributed_tpu.executor.abstract import Executor
from vllm_distributed_tpu.utils import run_method
from vllm_distributed_tpu.worker.worker import Worker


class UniProcExecutor(Executor):
    def _init_executor(self) -> None:
        self.worker = Worker(self.config, rank=0, is_driver_worker=True)
        # One resolver thread: fetches a dispatched step's results while
        # the engine thread issues the next dispatch (two in flight).
        self._resolve_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="vdt-resolve"
        )
        self.collective_rpc("init_device")
        self.collective_rpc("load_model")

    def execute_model(self, scheduler_output, non_block: bool = False):
        if self.config.kv_transfer_config is not None:
            return super().execute_model(scheduler_output, non_block)
        out = self.worker.execute_model(scheduler_output, defer=True)
        if callable(out):
            if non_block:
                return self._resolve_pool.submit(out)
            return out()
        if non_block:
            fut: concurrent.futures.Future = concurrent.futures.Future()
            fut.set_result(out)
            return fut
        return out

    def shutdown(self) -> None:
        self._resolve_pool.shutdown(wait=False)

    def collective_rpc(
        self,
        method: str,
        args: tuple = (),
        kwargs: dict | None = None,
        *,
        unique_reply_rank: int | None = None,
        non_block: bool = False,
        timeout: float | None = None,
    ) -> Any:
        result = run_method(self.worker, method, args, kwargs or {})
        if non_block:
            fut: concurrent.futures.Future = concurrent.futures.Future()
            fut.set_result(result)
            return fut
        return result if unique_reply_rank is not None else [result]
