from vllm_distributed_tpu.executor.abstract import Executor
from vllm_distributed_tpu.executor.uniproc import UniProcExecutor

__all__ = ["Executor", "UniProcExecutor"]
