"""KV-output aggregation — the disaggregated-prefill hook.

The reference gates this on ``vllm_config.kv_transfer_config``: with a
KV connector configured, execute_model fans out to ALL workers and the
per-worker outputs are merged by vLLM's KVOutputAggregator
(launch.py:295-296, 338-349; SURVEY.md §3.4).  The wrapper only routes
outputs — the transfer itself lives behind the connector interface —
and this rebuild matches that scope: sampled tokens come from the
designated output rank, while per-worker KV-transfer progress
(request ids whose KV finished sending/receiving on that worker) is
merged across the whole world, because a request's KV movement is only
complete when EVERY shard-holder is done.
"""

from __future__ import annotations

from vllm_distributed_tpu.outputs import ModelRunnerOutput


class KVOutputAggregator:
    def __init__(self, world_size: int) -> None:
        self.world_size = world_size
        # req_id -> number of workers still to report completion.
        self._send_remaining: dict[str, int] = {}
        self._recv_remaining: dict[str, int] = {}

    def aggregate(
        self, outputs: list[ModelRunnerOutput | None], output_rank: int
    ) -> ModelRunnerOutput:
        """Merge one step's per-worker outputs: model results from
        `output_rank`, KV-transfer progress from everyone (a request is
        done moving KV only when all world_size workers reported it)."""
        base = outputs[output_rank]
        if base is None:
            raise ValueError(
                f"output rank {output_rank} returned no output"
            )
        finished_sending: set[str] = set()
        finished_recving: set[str] = set()
        for out in outputs:
            if out is None:
                continue
            for req_id in out.kv_finished_sending:
                left = self._send_remaining.get(req_id, self.world_size) - 1
                if left:
                    self._send_remaining[req_id] = left
                else:
                    self._send_remaining.pop(req_id, None)
                    finished_sending.add(req_id)
            for req_id in out.kv_finished_recving:
                left = self._recv_remaining.get(req_id, self.world_size) - 1
                if left:
                    self._recv_remaining[req_id] = left
                else:
                    self._recv_remaining.pop(req_id, None)
                    finished_recving.add(req_id)
        base.kv_finished_sending = finished_sending
        base.kv_finished_recving = finished_recving
        return base

    def forget(self, req_id: str) -> None:
        """Drop partial progress for a request that left the system
        (finished/aborted) before all workers reported — otherwise the
        remaining-counts grow without bound, and a reused request id
        would complete early."""
        self._send_remaining.pop(req_id, None)
        self._recv_remaining.pop(req_id, None)
