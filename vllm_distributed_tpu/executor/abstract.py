"""Executor interface — the engine↔worker seam.

Mirrors the vLLM v1 Executor contract the reference plugs CustomExecutor
into (launch.py:45, 60-388; SURVEY.md §2.3): `_init_executor`,
`collective_rpc`, `execute_model`, `check_health`,
`register_failure_callback`, `max_concurrent_batches`.  The engine only
ever talks to this interface, so swapping uniproc ↔ multiproc ↔
multihost is a config change (`distributed_executor_backend`), exactly
the injection point the reference exploits (launch.py:400-405).
"""

from __future__ import annotations

import concurrent.futures
from typing import Any, Callable

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.distributed.failure import HostFailure
from vllm_distributed_tpu.engine.scheduler import SchedulerOutput
from vllm_distributed_tpu.outputs import ModelRunnerOutput

FailureCallback = Callable[[], None]


class Executor:
    """Subclasses implement _init_executor + collective_rpc."""

    uses_ray = False
    # Whether this executor's deaths can carry a recoverable HostFailure
    # the engine supervisor (engine/supervisor.py) may rebuild from.
    # AsyncLLM skips request journaling entirely when False.
    supports_recovery = False

    def __init__(self, config: EngineConfig) -> None:
        self.config = config
        self.parallel_config = config.parallel_config
        self.scheduler_config = config.scheduler_config
        self.is_failed = False
        # First HostFailure recorded wins: later kill-path echoes of the
        # same incident must not overwrite the root attribution.
        self.failure_info: HostFailure | None = None
        self.failure_callback: FailureCallback | None = None
        # EngineMetrics hook, set by LLMEngine after boot; executors that
        # emit liveness metrics (heartbeat latency, host_up) must
        # None-check it — heartbeats start before the engine exists.
        self.metrics = None
        self._init_executor()

    # ---- to implement ----
    def _init_executor(self) -> None:
        raise NotImplementedError

    def collective_rpc(
        self,
        method: str,
        args: tuple = (),
        kwargs: dict | None = None,
        *,
        unique_reply_rank: int | None = None,
        non_block: bool = False,
        timeout: float | None = None,
    ) -> Any:
        """Invoke `method` on every worker; return the designated rank's
        reply (or a list of all replies when unique_reply_rank is None)."""
        raise NotImplementedError

    # ---- engine-facing surface ----
    @classmethod
    def get_class(cls, config: EngineConfig) -> type["Executor"]:
        backend = config.parallel_config.distributed_executor_backend
        if isinstance(backend, type) and issubclass(backend, Executor):
            return backend
        if backend in (None, "uniproc", "auto"):
            from vllm_distributed_tpu.executor.uniproc import UniProcExecutor

            return UniProcExecutor
        if backend == "multihost":
            from vllm_distributed_tpu.executor.multihost import (
                MultiHostExecutor,
            )

            return MultiHostExecutor
        raise ValueError(f"unknown executor backend {backend!r}")

    @property
    def output_rank(self) -> int:
        """Reply comes from the first TP rank of the last PP stage
        (reference: launch.py:304-314)."""
        world = self.parallel_config.world_size
        tp = self.parallel_config.tensor_parallel_size
        return world - tp if world > tp else 0

    @property
    def max_concurrent_batches(self) -> int:
        """In-flight dispatch depth.  The reference ties this to pp
        (launch.py:298-302); here fused-decode pipelining keeps two
        dispatches in flight whenever multi-step decode is on."""
        return 2 if self.scheduler_config.num_decode_steps > 1 else 1

    @property
    def num_reply_workers(self) -> int:
        """How many worker replies one collective_rpc returns (worker
        PROCESSES, not chips: 1 for uniproc, num_hosts for multihost)."""
        return 1

    @property
    def kv_output_aggregator(self):
        """Lazy KVOutputAggregator, built iff kv_transfer_config is set
        (the reference's gate, launch.py:295-296)."""
        agg = getattr(self, "_kv_aggregator", None)
        if agg is None:
            from vllm_distributed_tpu.executor.kv_aggregator import (
                KVOutputAggregator,
            )

            agg = KVOutputAggregator(self.num_reply_workers)
            self._kv_aggregator = agg
        return agg

    def execute_model(
        self, scheduler_output: SchedulerOutput, non_block: bool = False
    ) -> ModelRunnerOutput | concurrent.futures.Future:
        if self.config.kv_transfer_config is not None:
            # KV-connector path: fan out to ALL workers and merge
            # (launch.py:338-349).  Resolved inline — KV-transfer steps
            # are not decode-scan-pipelined.  The reply list is ordered
            # [driver, *others], so the canonical output is index 0.
            outputs = self.collective_rpc(
                "execute_model", (scheduler_output,)
            )
            result = self.kv_output_aggregator.aggregate(outputs, 0)
            if non_block:
                fut: concurrent.futures.Future = concurrent.futures.Future()
                fut.set_result(result)
                return fut
            return result
        return self.collective_rpc(
            "execute_model",
            (scheduler_output,),
            unique_reply_rank=self.output_rank,
            non_block=non_block,
        )

    def determine_num_pages(self) -> int:
        replies = self.collective_rpc("determine_num_pages")
        return min(replies)

    def initialize_cache(self, num_pages: int) -> None:
        self.collective_rpc("initialize_cache", (num_pages,))

    def warmup_decode(self) -> None:
        # Pre-compile the fused-decode programs for every batch
        # bucket (boot-time; keeps serving recompile-free).
        self.collective_rpc("warmup_decode")

    def warmup_prefill(self) -> None:
        """Pre-compile prefill token buckets on every worker (boot)."""
        self.collective_rpc("warmup_prefill")

    def register_failure_callback(self, callback: FailureCallback) -> None:
        """Engine asks to be told about worker loss (launch.py:316-320)."""
        if self.is_failed:
            callback()
        else:
            self.failure_callback = callback

    def _notify_failure(self, failure: HostFailure | None = None) -> None:
        if failure is not None and self.failure_info is None:
            self.failure_info = failure
        self.is_failed = True
        cb, self.failure_callback = self.failure_callback, None
        if cb is not None:
            cb()

    def check_health(self) -> None:
        if self.is_failed:
            raise RuntimeError("Executor failed.")
        self.collective_rpc("check_health", timeout=10.0)

    def shutdown(self) -> None:
        pass
