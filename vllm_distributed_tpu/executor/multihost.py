"""Multi-host executor: driver spans TPU-VM hosts over the RPC control
plane.

The TPU-native rebuild of the reference's CustomExecutor (launch.py:60-388,
SURVEY.md §2 C1): the engine host listens on VDT_SERVER_PORT, remote-host
agents dial in and offer a ``create_worker`` factory, the executor fills
one worker slot per host, then drives init/load/execute via
``collective_rpc``.  Key TPU deltas (SURVEY.md §7 design stance):

- One worker per HOST owning all its chips (vs. per-GPU processes), so
  the agent fan-out is per-host, not per-device.
- Tensor traffic never touches this layer: workers join one
  ``jax.distributed`` world (coordinator minted here, the analog of
  launch.py:94) and all collectives are compiled into the step program
  over ICI/DCN.  Only SchedulerOutput/ModelRunnerOutput control messages
  cross the RPC plane per step (same economy as SURVEY.md §3.3).
- The reply comes from host 0 — with SPMD every host computes identical
  sampled tokens, so `unique_reply_rank` suppresses duplicate payloads
  (the intent of launch.py:304-314's output_rank).

Failure contract (§3.5/§5.3): a lost agent after deployment kills the
executor (fail-fast); engine learns via register_failure_callback; the
supervisor (compose restart / systemd) reforms the deployment.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import os
import threading
import time
from dataclasses import dataclass
from typing import Any

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.distributed.rpc import RpcProxy
from vllm_distributed_tpu.distributed.rpc_transport import (
    StreamRpcTransport,
    prepare_peer_readloop,
)
from vllm_distributed_tpu.executor.abstract import Executor
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.utils import (
    get_distributed_init_method,
    get_ip,
    get_open_port,
    run_method,
)

logger = init_logger(__name__)


@dataclass
class RemoteHost:
    host_rank: int
    peer: Any
    worker: RpcProxy | None = None  # proxy to the remote WorkerHost
    in_use: bool = False
    address: str = ""


class MultiHostExecutor(Executor):
    """Requires parallel_config.num_hosts > 1 agents to dial in before
    boot completes (the reference blocks the same way, launch.py:269)."""

    # Overridable in tests to install a mock worker class on all hosts.
    worker_cls: str | None = None

    def _init_executor(self) -> None:
        pc = self.parallel_config
        self.num_hosts = pc.num_hosts
        self.port = envs.VDT_SERVER_PORT
        self.execute_timeout = envs.VDT_EXECUTE_MODEL_TIMEOUT_SECONDS
        self._remote_hosts: list[RemoteHost] = []
        self._hosts_ready = concurrent.futures.Future()
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, daemon=True, name="vdt-executor"
        )
        self._loop_thread.start()
        # Local (host 0) worker calls block on device work; serialize them
        # on one thread so call order matches the RPC order remotes see.
        self._local_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="vdt-local-worker"
        )
        # Local fetch_results runs off the dispatch thread (mirrors the
        # agent's split pools) so dispatch N+1 overlaps fetch N.
        self._local_fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="vdt-local-fetch"
        )
        # Resolver threads for in-flight steps (two dispatches in flight
        # at steady state; replaces thread-per-dispatch).
        self._gather_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="vdt-gather"
        )

        self.distributed_init_method = get_distributed_init_method(
            os.environ.get("VDT_HOST_IP") or get_ip(), get_open_port()
        )

        # Accept agents until every host slot is filled.
        fut = asyncio.run_coroutine_threadsafe(
            self._start_listener(), self._loop
        )
        fut.result(timeout=30)
        logger.info(
            "waiting for %d remote host(s) on port %d …",
            self.num_hosts - 1,
            self.port,
        )
        self._hosts_ready.result()
        logger.info("all %d hosts connected", self.num_hosts)

        # Build the local (host 0) worker in-process.
        self._local_worker = self._make_local_worker()

        # Create remote workers, then run the lifecycle: device init is
        # concurrent across hosts because jax.distributed.initialize
        # blocks until the whole world joins.
        asyncio.run_coroutine_threadsafe(
            self._create_remote_workers(), self._loop
        ).result(timeout=120)
        self.collective_rpc("init_device")
        self.collective_rpc("load_model")

    # ---- topology ----
    def _make_local_worker(self):
        if self.worker_cls is not None:
            import importlib

            mod, cls = self.worker_cls.rsplit(".", 1)
            worker_cls = getattr(importlib.import_module(mod), cls)
        else:
            from vllm_distributed_tpu.worker.worker import Worker as worker_cls
        return worker_cls(
            self.config,
            rank=0,
            distributed_init_method=self.distributed_init_method,
            is_driver_worker=True,
        )

    async def _start_listener(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_agent, "0.0.0.0", self.port
        )

    async def _handle_agent(self, reader, writer) -> None:
        """One connection per remote host (reference handle_client,
        launch.py:99-144, minus the per-GPU pooling — one agent IS one
        host here)."""
        addr = writer.get_extra_info("peername")
        transport = StreamRpcTransport(reader, writer)
        peer, readloop = prepare_peer_readloop(transport, f"agent{addr}")
        host: RemoteHost | None = None
        try:
            if len(self._remote_hosts) >= self.num_hosts - 1:
                logger.warning("surplus agent from %s; rejecting", addr)
                writer.close()
                return
            # Validate the host's chips before giving it a slot (the
            # reference warns and skips short nodes, launch.py:226-231;
            # round 2 published host_info but never read it).
            readloop_task = asyncio.ensure_future(readloop())
            try:
                # Generous timeout: the agent's probe subprocess imports
                # jax, which initializes the TPU runtime cold.
                info = await asyncio.wait_for(self._host_info(peer), 60)
            except Exception as e:  # noqa: BLE001
                logger.warning("agent %s: host_info failed (%s)", addr, e)
                writer.close()
                return await self._await_readloop(readloop_task)
            required = max(self.parallel_config.world_size // self.num_hosts, 1)
            if (
                info.get("platform") == "tpu"
                and info.get("num_chips", 0) < required
            ):
                logger.warning(
                    "agent %s offers %d chip(s); deployment needs %d per "
                    "host — skipping this host",
                    addr,
                    info.get("num_chips", 0),
                    required,
                )
                writer.close()
                return await self._await_readloop(readloop_task)
            # Re-check capacity: the host_info await above suspended this
            # handler, so another agent may have taken the last slot.
            if len(self._remote_hosts) >= self.num_hosts - 1:
                logger.warning("surplus agent from %s; rejecting", addr)
                writer.close()
                return await self._await_readloop(readloop_task)
            host = RemoteHost(
                host_rank=len(self._remote_hosts) + 1,
                peer=peer,
                address=str(addr),
            )
            self._remote_hosts.append(host)
            logger.info(
                "agent %s connected as host rank %d", addr, host.host_rank
            )
            if (
                len(self._remote_hosts) == self.num_hosts - 1
                and not self._hosts_ready.done()
            ):
                self._hosts_ready.set_result(True)
            await readloop_task
        except Exception as e:  # noqa: BLE001
            logger.warning("agent %s read loop ended: %s", addr, e)
        finally:
            if host is not None:
                if host.in_use:
                    # Deployment member lost: fail fast (launch.py:130-144).
                    logger.error(
                        "host rank %d (%s) lost — executor failed",
                        host.host_rank,
                        host.address,
                    )
                    self._notify_failure()
                elif host in self._remote_hosts:
                    self._remote_hosts.remove(host)

    async def _host_info(self, peer) -> dict:
        host_info = await peer.get_param("host_info")
        return await host_info()

    @staticmethod
    async def _await_readloop(task) -> None:
        """Drain a rejected agent's read loop (errors expected: we just
        closed its transport)."""
        try:
            await task
        except Exception:  # noqa: BLE001
            pass

    async def _create_remote_workers(self) -> None:
        env = envs.replication_env()
        for host in self._remote_hosts:
            create_worker = await host.peer.get_param("create_worker")
            host.worker = await create_worker(
                self.config,
                host.host_rank,
                self.num_hosts,
                self.distributed_init_method,
                env,
                self.worker_cls,
            )
            host.in_use = True

    # ---- dispatch ----
    def collective_rpc(
        self,
        method: str,
        args: tuple = (),
        kwargs: dict | None = None,
        *,
        unique_reply_rank: int | None = None,
        non_block: bool = False,
        timeout: float | None = None,
    ) -> Any:
        if self.is_failed:
            raise RuntimeError("Executor failed.")
        kwargs = kwargs or {}
        timeout = timeout or self.execute_timeout

        local_fut = self._local_pool.submit(
            run_method, self._local_worker, method, args, kwargs
        )
        remote_futs = [
            asyncio.run_coroutine_threadsafe(
                host.worker.run(method, args, kwargs), self._loop
            )
            for host in self._remote_hosts
            if host.worker is not None
        ]
        futures = [local_fut, *remote_futs]

        if non_block:
            return self._gather_pool.submit(
                self._gather, futures, unique_reply_rank, timeout
            )
        return self._gather(futures, unique_reply_rank, timeout)

    def execute_model(self, scheduler_output, non_block: bool = False):
        """Blocking path: one collective execute_model RPC.  Pipelined
        path (non_block): two-phase dispatch_model / fetch_results so
        the per-step DCN round trip overlaps device compute — the
        steady-state amortization the fused-decode design exists for
        (SURVEY.md §3.3; reference's in-flight batches,
        launch.py:298-302).

        Per-peer ordering: dispatch and fetch RPCs are scheduled on the
        executor loop from this (engine) thread, in program order; the
        agent routes the two verbs to separate single-thread pools, so
        dispatches stay ordered, fetches stay ordered, and fetch N never
        blocks dispatch N+1."""
        if not non_block or self.config.kv_transfer_config is not None:
            return super().execute_model(scheduler_output, non_block=False)
        if self.is_failed:
            raise RuntimeError("Executor failed.")
        step_id = scheduler_output.step_id
        local_d = self._local_pool.submit(
            run_method,
            self._local_worker,
            "dispatch_model",
            (scheduler_output,),
            {},
        )
        remote_d = [
            asyncio.run_coroutine_threadsafe(
                host.worker.run("dispatch_model", (scheduler_output,), {}),
                self._loop,
            )
            for host in self._remote_hosts
            if host.worker is not None
        ]

        def _local_fetch():
            local_d.result()  # dispatch errors surface here, in order
            return run_method(
                self._local_worker, "fetch_results", (step_id,), {}
            )

        local_f = self._local_fetch_pool.submit(_local_fetch)
        remote_f = [
            asyncio.run_coroutine_threadsafe(
                host.worker.run("fetch_results", (step_id,), {}), self._loop
            )
            for host in self._remote_hosts
            if host.worker is not None
        ]
        return self._gather_pool.submit(
            self._gather,
            [local_f, *remote_f, *remote_d],
            0,  # host 0 (local driver) holds the canonical output
            self.execute_timeout,
        )

    def _gather(self, futures, unique_reply_rank, timeout):
        # One overall deadline, not timeout × num_hosts.
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        try:
            results = [
                f.result(
                    timeout=None
                    if deadline is None
                    else max(deadline - time.monotonic(), 0.0)
                )
                for f in futures
            ]
        except Exception as e:  # noqa: BLE001
            logger.error("collective_rpc failed: %s", e)
            self._notify_failure()
            raise RuntimeError("Executor failed.") from e
        if unique_reply_rank is not None:
            return results[unique_reply_rank]
        return results

    @property
    def output_rank(self) -> int:
        return 0  # SPMD: host 0's copy of the output is canonical.

    @property
    def num_reply_workers(self) -> int:
        return self.num_hosts

    def _notify_failure(self) -> None:
        # Errors during an intentional shutdown are teardown noise, not
        # deployment failures — don't mark the engine dead for them.
        if getattr(self, "_shutting_down", False):
            return
        super()._notify_failure()

    def shutdown(self) -> None:
        self._shutting_down = True
        # Clean jax.distributed teardown on every host BEFORE dropping
        # the control plane (the shutdown barrier needs all tasks).
        try:
            self.collective_rpc("shutdown", timeout=15.0)
        except Exception:  # noqa: BLE001 — failed/partial deployments
            pass
        for host in self._remote_hosts:
            try:
                host.peer.kill("executor shutdown")
            except Exception:  # noqa: BLE001
                pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._local_pool.shutdown(wait=False)
        self._local_fetch_pool.shutdown(wait=False)
        self._gather_pool.shutdown(wait=False)
