"""Multi-host executor: driver spans TPU-VM hosts over the RPC control
plane.

The TPU-native rebuild of the reference's CustomExecutor (launch.py:60-388,
SURVEY.md §2 C1): the engine host listens on VDT_SERVER_PORT, remote-host
agents dial in and offer a ``create_worker`` factory, the executor fills
one worker slot per host, then drives init/load/execute via
``collective_rpc``.  Key TPU deltas (SURVEY.md §7 design stance):

- One worker per HOST owning all its chips (vs. per-GPU processes), so
  the agent fan-out is per-host, not per-device.
- Tensor traffic never touches this layer: workers join one
  ``jax.distributed`` world (coordinator minted here, the analog of
  launch.py:94) and all collectives are compiled into the step program
  over ICI/DCN.  Only SchedulerOutput/ModelRunnerOutput control messages
  cross the RPC plane per step (same economy as SURVEY.md §3.3).
- The reply comes from host 0 — with SPMD every host computes identical
  sampled tokens, so `unique_reply_rank` suppresses duplicate payloads
  (the intent of launch.py:304-314's output_rank).

Failure contract (§3.5/§5.3): a lost agent after deployment kills the
executor (fail-fast); engine learns via register_failure_callback; the
in-process EngineSupervisor (engine/supervisor.py) tears this executor
down and builds a fresh one that re-listens on the same port while the
agents redial — the external supervisor (compose restart / systemd) is
only the fallback once the restart policy is exhausted.  Every
kill path produces a ``HostFailure`` naming the host and lifecycle phase
(connect/init/execute/heartbeat); the FIRST one recorded is the root
attribution surfaced on /health.  Liveness does not wait for traffic:
the driver heartbeats every agent on VDT_HEARTBEAT_INTERVAL_SECONDS and
VDT_HEARTBEAT_MISS_THRESHOLD consecutive misses trip failure even on an
idle deployment (vLLM's engine only notices a dead worker when an
in-flight execute exhausts its timeout; over DCN a wedged-but-connected
host is a routine failure mode, not an exotic one).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import os
import threading
import time
from dataclasses import dataclass
from typing import Any

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.distributed.failure import (
    PHASE_CONNECT,
    PHASE_EXECUTE,
    PHASE_HEARTBEAT,
    PHASE_INIT,
    HostFailure,
)
from vllm_distributed_tpu.distributed.rpc import (
    RpcProxy,
    apply_oneway,
    apply_with_timeout,
)
from vllm_distributed_tpu.engine.step_delta import StepDeltaEncoder
from vllm_distributed_tpu.distributed.rpc_transport import (
    StreamRpcTransport,
    prepare_peer_readloop,
)
from vllm_distributed_tpu.executor.abstract import Executor
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.tracing import NOOP_SPAN, get_tracer
from vllm_distributed_tpu.utils import (
    get_distributed_init_method,
    get_ip,
    get_open_port,
    run_method,
)

logger = init_logger(__name__)

# (host_rank, address) tag attached to every gathered future so timeouts
# and errors are attributable to the offending host.
_LOCAL_ORIGIN = (0, "local")


@dataclass
class RemoteHost:
    host_rank: int
    peer: Any
    worker: RpcProxy | None = None  # proxy to the remote WorkerHost
    in_use: bool = False
    address: str = ""
    transport: Any = None  # closing it unblocks the read loop


class _InflightStep:
    """Driver-side record of one step pushed into the streams: which
    hosts still owe an ack, the canonical (host 0) result, and the
    event `_finish_step` waits on — deadline-bounded, so a silent host
    turns into an attributed ``HostFailure``, never a wedged engine."""

    __slots__ = (
        "step_id",
        "expected",
        "done",
        "origins",
        "result",
        "error",
        "event",
        "trace_ctx",
        "t_mono",
        "t_wall",
    )

    def __init__(
        self,
        step_id: int,
        origins: dict[int, str],
        trace_ctx: tuple | None,
    ) -> None:
        self.step_id = step_id
        self.expected = set(origins)
        self.done: set[int] = set()
        self.origins = origins  # host_rank -> address (attribution)
        self.result = None
        self.error: HostFailure | str | None = None
        self.event = threading.Event()
        self.trace_ctx = trace_ctx
        self.t_mono = time.monotonic()
        self.t_wall = time.time()


class MultiHostExecutor(Executor):
    """Requires parallel_config.num_hosts > 1 agents to dial in before
    boot completes (the reference blocks the same way, launch.py:269)."""

    # Overridable in tests to install a mock worker class on all hosts.
    worker_cls: str | None = None
    # Deaths carry per-host HostFailure attribution the supervisor can
    # recover from (agents redial, the executor rebuilds in-process).
    supports_recovery = True

    def _init_executor(self) -> None:
        pc = self.parallel_config
        self.num_hosts = pc.num_hosts
        self.port = envs.VDT_SERVER_PORT
        self.execute_timeout = envs.VDT_EXECUTE_MODEL_TIMEOUT_SECONDS
        self.heartbeat_interval = envs.VDT_HEARTBEAT_INTERVAL_SECONDS
        self.heartbeat_threshold = max(1, envs.VDT_HEARTBEAT_MISS_THRESHOLD)
        self._remote_hosts: list[RemoteHost] = []
        self._heartbeat_tasks: list[concurrent.futures.Future] = []
        self._creating_host: RemoteHost | None = None
        self._hosts_ready = concurrent.futures.Future()
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, daemon=True, name="vdt-executor"
        )
        self._loop_thread.start()
        # Local (host 0) worker calls block on device work; serialize them
        # on one thread so call order matches the RPC order remotes see.
        self._local_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="vdt-local-worker"
        )
        # Local fetch_results runs off the dispatch thread (mirrors the
        # agent's split pools) so dispatch N+1 overlaps fetch N.
        self._local_fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="vdt-local-fetch"
        )
        # Resolver threads for in-flight steps (two dispatches in flight
        # at steady state; replaces thread-per-dispatch).
        self._gather_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(
                2, self.scheduler_config.max_concurrent_dispatches
            ),
            thread_name_prefix="vdt-gather",
        )
        # Persistent per-host step streams (ISSUE 7): per-step control
        # messages become one one-way frame each way instead of
        # request/reply pairs.  Disabled for KV-transfer deployments
        # (their steps fan out through the aggregating collective path).
        self._stream_enabled = (
            envs.VDT_STEP_STREAMS
            and self.config.kv_transfer_config is None
        )
        self._stream_depth = max(
            envs.VDT_STEP_STREAM_DEPTH,
            2 * self.scheduler_config.max_concurrent_dispatches,
        )
        self._streams_started = False
        self._encoder = StepDeltaEncoder()
        self._local_runner = None
        self._inflight_steps: dict[int, _InflightStep] = {}
        self._inflight_lock = threading.Lock()

        self.distributed_init_method = get_distributed_init_method(
            envs.VDT_HOST_IP or get_ip(), get_open_port()
        )

        # Accept agents until every host slot is filled.
        fut = asyncio.run_coroutine_threadsafe(
            self._start_listener(), self._loop
        )
        fut.result(timeout=30)
        logger.info(
            "waiting for %d remote host(s) on port %d …",
            self.num_hosts - 1,
            self.port,
        )
        try:
            self._boot()
        except Exception:
            # A half-booted executor must not leak its loop thread, pools,
            # or listening socket when the constructor raises.
            self._teardown(drain_workers=False)
            raise

    def _boot(self) -> None:
        connect_timeout = envs.VDT_CONNECT_TIMEOUT_SECONDS
        try:
            self._hosts_ready.result(timeout=connect_timeout or None)
        except concurrent.futures.TimeoutError as e:
            failure = HostFailure(
                host_rank=-1,
                address="",
                phase=PHASE_CONNECT,
                message=(
                    f"only {len(self._remote_hosts)}/{self.num_hosts - 1} "
                    f"agent(s) dialed in within {connect_timeout:.0f}s"
                ),
            )
            self._notify_failure(failure)
            raise RuntimeError(f"Executor failed: {failure.describe()}") from e
        logger.info("all %d hosts connected", self.num_hosts)

        # Build the local (host 0) worker in-process.
        self._local_worker = self._make_local_worker()

        # Create remote workers, then run the lifecycle: device init is
        # concurrent across hosts because jax.distributed.initialize
        # blocks until the whole world joins.
        try:
            asyncio.run_coroutine_threadsafe(
                self._create_remote_workers(), self._loop
            ).result(timeout=envs.VDT_INIT_TIMEOUT_SECONDS or None)
        except Exception as e:
            host = self._creating_host
            failure = HostFailure.from_exception(
                host.host_rank if host is not None else -1,
                host.address if host is not None else "",
                PHASE_INIT,
                "remote worker creation failed"
                if not isinstance(e, concurrent.futures.TimeoutError)
                else (
                    "remote worker creation timed out after "
                    f"{envs.VDT_INIT_TIMEOUT_SECONDS:.0f}s"
                ),
                e,
            )
            self._notify_failure(failure)
            raise RuntimeError(f"Executor failed: {failure.describe()}") from e
        # Liveness from here on: a host that wedges during device init,
        # weight load, or an idle stretch is caught by heartbeats, not by
        # an eventual request timeout.
        self._start_heartbeats()
        self.collective_rpc("init_device", _phase=PHASE_INIT)
        self.collective_rpc("load_model", _phase=PHASE_INIT)

    # ---- topology ----
    def _make_local_worker(self):
        if self.worker_cls is not None:
            import importlib

            mod, cls = self.worker_cls.rsplit(".", 1)
            worker_cls = getattr(importlib.import_module(mod), cls)
        else:
            from vllm_distributed_tpu.worker.worker import Worker as worker_cls
        return worker_cls(
            self.config,
            rank=0,
            distributed_init_method=self.distributed_init_method,
            is_driver_worker=True,
        )

    async def _start_listener(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_agent, "0.0.0.0", self.port
        )

    async def _handle_agent(self, reader, writer) -> None:
        """One connection per remote host (reference handle_client,
        launch.py:99-144, minus the per-GPU pooling — one agent IS one
        host here)."""
        addr = writer.get_extra_info("peername")
        transport = StreamRpcTransport(reader, writer)
        peer, readloop = prepare_peer_readloop(transport, f"agent{addr}")
        host: RemoteHost | None = None
        try:
            if len(self._remote_hosts) >= self.num_hosts - 1:
                logger.warning("surplus agent from %s; rejecting", addr)
                writer.close()
                return
            # Validate the host's chips before giving it a slot (the
            # reference warns and skips short nodes, launch.py:226-231;
            # round 2 published host_info but never read it).
            readloop_task = asyncio.ensure_future(readloop())
            try:
                # Generous timeout: the agent's probe subprocess imports
                # jax, which initializes the TPU runtime cold.
                info = await asyncio.wait_for(self._host_info(peer), 60)
            except Exception as e:  # noqa: BLE001
                logger.warning("agent %s: host_info failed (%s)", addr, e)
                writer.close()
                return await self._await_readloop(readloop_task)
            required = max(self.parallel_config.world_size // self.num_hosts, 1)
            if info.get("platform") == "unknown" or (
                info.get("platform") == "tpu"
                and info.get("num_chips", 0) < required
            ):
                logger.warning(
                    "agent %s offers %d chip(s) on platform %r; deployment "
                    "needs %d per host — skipping this host",
                    addr,
                    info.get("num_chips", 0),
                    info.get("platform"),
                    required,
                )
                writer.close()
                return await self._await_readloop(readloop_task)
            # Re-check capacity: the host_info await above suspended this
            # handler, so another agent may have taken the last slot.
            if len(self._remote_hosts) >= self.num_hosts - 1:
                logger.warning("surplus agent from %s; rejecting", addr)
                writer.close()
                return await self._await_readloop(readloop_task)
            host = RemoteHost(
                host_rank=len(self._remote_hosts) + 1,
                peer=peer,
                address=str(addr),
                transport=transport,
            )
            self._remote_hosts.append(host)
            logger.info(
                "agent %s connected as host rank %d", addr, host.host_rank
            )
            if (
                len(self._remote_hosts) == self.num_hosts - 1
                and not self._hosts_ready.done()
            ):
                self._hosts_ready.set_result(True)
            # vdt-lint: disable=unbounded-wait — serves this agent until
            # disconnect by contract; the heartbeat loop owns liveness
            # and closes the transport to end it.
            await readloop_task
        except Exception as e:  # noqa: BLE001
            logger.warning("agent %s read loop ended: %s", addr, e)
        finally:
            if host is not None:
                if host.in_use and not getattr(
                    self, "_shutting_down", False
                ):
                    # Deployment member lost: fail fast (launch.py:130-144)
                    # with the host named.  First recorded failure wins,
                    # so a heartbeat/execute attribution that triggered
                    # this kill is preserved as the root cause.
                    failure = HostFailure(
                        host_rank=host.host_rank,
                        address=host.address,
                        phase=PHASE_CONNECT,
                        message=(
                            "connection to agent lost "
                            f"({host.peer.killed_reason or 'EOF'})"
                        ),
                    )
                    logger.error("%s — executor failed", failure.describe())
                    if self.metrics is not None:
                        self.metrics.record_host_down(host.host_rank)
                    self._notify_failure(failure)
                elif host in self._remote_hosts:
                    self._remote_hosts.remove(host)

    async def _host_info(self, peer) -> dict:
        # vdt-lint: disable=unbounded-wait — _handle_agent wraps this
        # whole coroutine in asyncio.wait_for(..., 60).
        host_info = await peer.get_param("host_info")
        return await host_info()

    @staticmethod
    async def _await_readloop(task) -> None:
        """Drain a rejected agent's read loop (errors expected: we just
        closed its transport)."""
        try:
            # vdt-lint: disable=unbounded-wait — the transport is already
            # closed, so the loop ends on the EOF/error it is about to read.
            await task
        except Exception as e:  # noqa: BLE001
            logger.debug("rejected agent read loop ended: %s", e)

    async def _create_remote_workers(self) -> None:
        env = envs.replication_env()
        # The driver's RESOLVED tracing config wins over whatever
        # VDT_TRACING literal happens to sit in its environment (e.g.
        # VDT_TRACING=0 + --enable-tracing): agents must agree with the
        # driver or every trace silently loses its worker-side spans.
        obs = self.config.observability_config
        if getattr(obs, "enable_tracing", False):
            env["VDT_TRACING"] = "1"
            env.setdefault(
                "VDT_TRACE_RING_SIZE", str(obs.trace_ring_size)
            )
        for host in self._remote_hosts:
            # Left pointing at the failing host on exception: _boot reads
            # it AFTER .result() re-raises, so no finally-clear here (it
            # would wipe the attribution before the engine thread looks).
            self._creating_host = host
            # vdt-lint: disable=unbounded-wait — _boot bounds the whole
            # coroutine with .result(timeout=VDT_INIT_TIMEOUT_SECONDS).
            create_worker = await host.peer.get_param("create_worker")
            host.worker = await create_worker(
                self.config,
                host.host_rank,
                self.num_hosts,
                self.distributed_init_method,
                env,
                self.worker_cls,
            )
            host.in_use = True
        self._creating_host = None

    # ---- liveness ----
    def _start_heartbeats(self) -> None:
        if self.heartbeat_interval <= 0:
            return
        for host in self._remote_hosts:
            self._heartbeat_tasks.append(
                asyncio.run_coroutine_threadsafe(
                    self._heartbeat_loop(host), self._loop
                )
            )

    async def _heartbeat_loop(self, host: RemoteHost) -> None:
        """Ping one agent every interval; N consecutive misses mark the
        host dead WITHOUT waiting for a request to hit the execute
        timeout.  A miss is a deadline-bounded apply whose pending slot
        is reclaimed (rpc.apply_with_timeout), so lost pongs never leak
        futures no matter how long the deployment runs."""
        interval = self.heartbeat_interval
        threshold = self.heartbeat_threshold
        try:
            ping = await asyncio.wait_for(
                host.peer.get_param("ping"), interval * threshold
            )
        except Exception as e:  # noqa: BLE001
            if not host.peer.killed:
                logger.warning(
                    "host rank %d (%s): no ping param (%s); heartbeat "
                    "liveness disabled for this host",
                    host.host_rank,
                    host.address,
                    e,
                )
            return
        misses = 0
        seq = 0
        tracer = get_tracer()
        while not host.peer.killed:
            t0 = time.monotonic()
            wall0 = time.time()
            seq += 1
            try:
                pong = await apply_with_timeout(ping, interval, seq)
                rtt = time.monotonic() - t0
                misses = 0
                if self.metrics is not None:
                    self.metrics.record_heartbeat(host.host_rank, rtt)
                if (
                    tracer.enabled
                    and isinstance(pong, (list, tuple))
                    and len(pong) == 2
                ):
                    # The pong carries the agent's wall clock; assuming
                    # a symmetric path, it was read mid-RTT.  Low-RTT
                    # samples give the per-host offset used to place
                    # worker-side trace spans on the driver's timeline.
                    tracer.set_clock_offset(
                        f"host{host.host_rank}",
                        pong[1] - (wall0 + rtt / 2.0),
                        rtt,
                    )
            except asyncio.TimeoutError:
                misses += 1
                logger.warning(
                    "host rank %d (%s): heartbeat miss %d/%d",
                    host.host_rank,
                    host.address,
                    misses,
                    threshold,
                )
            except Exception as e:  # noqa: BLE001
                if host.peer.killed:
                    return  # disconnect path owns this failure
                misses += 1
                logger.warning(
                    "host rank %d (%s): heartbeat error %s — miss %d/%d",
                    host.host_rank,
                    host.address,
                    e,
                    misses,
                    threshold,
                )
            if misses >= threshold:
                failure = HostFailure(
                    host_rank=host.host_rank,
                    address=host.address,
                    phase=PHASE_HEARTBEAT,
                    message=(
                        f"{misses} consecutive heartbeats missed "
                        f"({interval:.1f}s interval)"
                    ),
                )
                logger.error("%s — executor failed", failure.describe())
                if self.metrics is not None:
                    self.metrics.record_host_down(host.host_rank)
                self._notify_failure(failure)
                host.peer.kill(failure.describe())
                if host.transport is not None:
                    host.transport.close()  # unblock the read loop
                return
            await asyncio.sleep(
                max(0.0, interval - (time.monotonic() - t0))
            )

    def _cancel_heartbeats(self) -> None:
        tasks, self._heartbeat_tasks = self._heartbeat_tasks, []
        for task in tasks:
            task.cancel()

    # ---- dispatch ----
    def collective_rpc(
        self,
        method: str,
        args: tuple = (),
        kwargs: dict | None = None,
        *,
        unique_reply_rank: int | None = None,
        non_block: bool = False,
        timeout: float | None = None,
        _phase: str = PHASE_EXECUTE,
    ) -> Any:
        if self.is_failed:
            raise RuntimeError("Executor failed.")
        kwargs = kwargs or {}
        timeout = timeout or self.execute_timeout

        trace_ctx = self._step_trace_ctx(method, args)
        payload = self._payload_bytes(args) if trace_ctx is not None else None
        step_id = (
            getattr(args[0], "step_id", None)
            if trace_ctx is not None and args
            else None
        )
        with self._dispatch_span(trace_ctx, 0, method, payload, step_id):
            local_fut = self._local_pool.submit(
                run_method, self._local_worker, method, args, kwargs
            )
        live = [h for h in self._remote_hosts if h.worker is not None]
        remote_futs = []
        for host in live:
            # The dispatch span is the parent the worker-side spans
            # attach to: host.worker.run builds the RPC frame inside
            # this block, so the frame carries the span's context.
            with self._dispatch_span(
                trace_ctx, host.host_rank, method, payload, step_id
            ):
                remote_futs.append(
                    asyncio.run_coroutine_threadsafe(
                        host.worker.run(method, args, kwargs), self._loop
                    )
                )
        futures = [local_fut, *remote_futs]
        origins = [_LOCAL_ORIGIN] + [(h.host_rank, h.address) for h in live]

        if non_block:
            return self._gather_pool.submit(
                self._gather, futures, origins, unique_reply_rank, timeout,
                _phase, trace_ctx, step_id,
            )
        return self._gather(futures, origins, unique_reply_rank, timeout,
                            _phase, trace_ctx, step_id)

    def execute_model(self, scheduler_output, non_block: bool = False):
        """Step dispatch, in order of preference:

        1. **Persistent step streams** (default, ``VDT_STEP_STREAMS``):
           the step is delta-compressed against the worker mirrors
           (engine/step_delta.py), serialized ONCE, and pushed to every
           host as a single one-way frame; results come back as one-way
           acks collected by ``_on_step_result``.  Every step — blocking
           prefills included — flows through the stream so the encoder
           and the per-host mirrors stay in lockstep.
        2. Legacy two-phase dispatch_model/fetch_results RPC pairs
           (``VDT_STEP_STREAMS=0``), the pre-stream pipelining path.
        3. Blocking collective execute_model (legacy non-pipelined, and
           all KV-transfer deployments — their steps fan out through the
           aggregating collective path).

        Per-peer ordering: stream frames (and legacy dispatch/fetch
        RPCs) are scheduled on the executor loop from this (engine)
        thread, in program order, over one TCP stream per host — so
        every host's mirror sees every step in step-id order."""
        if self.config.kv_transfer_config is not None:
            return super().execute_model(scheduler_output, non_block=False)
        if self.is_failed:
            raise RuntimeError("Executor failed.")
        if self._stream_enabled:
            self._ensure_step_streams()
            return self._stream_execute(scheduler_output, non_block)
        if not non_block:
            return super().execute_model(scheduler_output, non_block=False)
        step_id = scheduler_output.step_id
        trace_ctx = self._step_trace_ctx("dispatch_model", (scheduler_output,))
        payload = (
            self._payload_bytes((scheduler_output,))
            if trace_ctx is not None
            else None
        )
        with self._dispatch_span(
            trace_ctx, 0, "dispatch_model", payload, step_id
        ):
            local_d = self._local_pool.submit(
                run_method,
                self._local_worker,
                "dispatch_model",
                (scheduler_output,),
                {},
            )
        live = [h for h in self._remote_hosts if h.worker is not None]
        remote_d = []
        remote_f = []
        for host in live:
            # Both phase RPCs of one host parent to its dispatch span
            # (the frames are built inside this block), so worker-side
            # dispatch AND fetch spans chain into the step's trace.
            with self._dispatch_span(
                trace_ctx, host.host_rank, "dispatch_model", payload, step_id
            ):
                remote_d.append(
                    asyncio.run_coroutine_threadsafe(
                        host.worker.run(
                            "dispatch_model", (scheduler_output,), {}
                        ),
                        self._loop,
                    )
                )
                remote_f.append(
                    asyncio.run_coroutine_threadsafe(
                        host.worker.run("fetch_results", (step_id,), {}),
                        self._loop,
                    )
                )

        def _local_fetch():
            # Dispatch errors surface here, in order.  Deadline-bounded:
            # a wedged local dispatch must fail this step's gather, not
            # hang the fetch-pool thread forever.
            local_d.result(timeout=self.execute_timeout)
            return run_method(
                self._local_worker, "fetch_results", (step_id,), {}
            )

        local_f = self._local_fetch_pool.submit(_local_fetch)
        remote_origins = [(h.host_rank, h.address) for h in live]
        return self._gather_pool.submit(
            self._gather,
            [local_f, *remote_f, *remote_d],
            [_LOCAL_ORIGIN, *remote_origins, *remote_origins],
            0,  # host 0 (local driver) holds the canonical output
            self.execute_timeout,
            PHASE_EXECUTE,
            trace_ctx,
            step_id,
        )

    # ---- persistent step streams (ISSUE 7) ----
    def _ensure_step_streams(self) -> None:
        """Lazy one-time stream start (first dispatched step): a local
        in-process runner for host 0, and one ``start_step_stream`` RPC
        per remote host handing it the per-host ack callback."""
        if self._streams_started:
            return
        from vllm_distributed_tpu.worker.step_stream import StepStreamRunner

        def _local_deliver(step_id, result, error, spans, _ctx):
            self._on_step_result(0, step_id, result, error, spans or [])

        self._local_runner = StepStreamRunner(
            self._local_worker,
            _local_deliver,
            depth=self._stream_depth,
            name="local",
        )
        for host in self._remote_hosts:
            if host.worker is None:
                continue
            try:
                asyncio.run_coroutine_threadsafe(
                    host.worker.start_step_stream(
                        self._make_remote_deliver(host.host_rank),
                        self._stream_depth,
                    ),
                    self._loop,
                ).result(timeout=self.execute_timeout)
            except Exception as e:  # noqa: BLE001 — a host that cannot
                # start its run loop fails the deployment, attributed.
                failure = HostFailure.from_exception(
                    host.host_rank,
                    host.address,
                    PHASE_EXECUTE,
                    "step stream start failed",
                    e,
                )
                self._notify_failure(failure)
                raise RuntimeError(
                    f"Executor failed: {failure.describe()}"
                ) from e
        self._streams_started = True

    def _make_remote_deliver(self, host_rank: int):
        """Driver-side ack sink proxied to one agent: runs on the
        executor loop when the host's one-way ack frame arrives."""
        import cloudpickle

        def step_ack(step_id, payload=None, error=None, spans=None):
            result = (
                cloudpickle.loads(payload) if payload is not None else None
            )
            self._on_step_result(
                host_rank, step_id, result, error, spans or []
            )

        step_ack.__name__ = f"step_ack_host{host_rank}"
        return step_ack

    def _stream_execute(self, scheduler_output, non_block: bool):
        step_id = scheduler_output.step_id
        tracer = get_tracer()
        trace_ctx = (
            getattr(scheduler_output, "trace_ctx", None)
            if tracer.enabled
            else None
        )
        frame = self._encoder.encode(
            scheduler_output, blocking=not non_block
        )
        # Serialize ONCE; every host send shares the same bytes (the
        # transport ships them as one sideband buffer per host, and the
        # payload_bytes span attribute is exact, not re-pickled).
        import cloudpickle

        frame_bytes = cloudpickle.dumps(frame)
        live = [h for h in self._remote_hosts if h.worker is not None]
        origins = {0: "local"}
        origins.update({h.host_rank: h.address for h in live})
        entry = _InflightStep(step_id, origins, trace_ctx)
        with self._inflight_lock:
            self._inflight_steps[step_id] = entry
        if self.is_failed:
            # A failure that landed after execute_model's gate but
            # before the insertion above raced _fail_inflight_steps'
            # snapshot — nobody else will release this entry, so fail
            # it here (EOF-fast, never deadline-slow).
            cause = self.failure_info
            entry.error = entry.error or (
                cause if cause is not None else "executor failed"
            )
            entry.event.set()
        with self._dispatch_span(
            trace_ctx, 0, "stream_step", len(frame_bytes), step_id
        ):
            self._local_runner.submit(frame, None)
        for host in live:
            span = self._dispatch_span(
                trace_ctx,
                host.host_rank,
                "stream_step",
                len(frame_bytes),
                step_id,
            )
            with span:
                # The span's context rides the frame so the host's
                # worker.execute/serialize/reply spans (shipped back in
                # the ack) chain into this step's trace.
                ctx = span.ctx if trace_ctx is not None else None
                fut = asyncio.run_coroutine_threadsafe(
                    apply_oneway(
                        host.worker,
                        "stream_step",
                        frame_bytes,
                        list(ctx) if ctx is not None else None,
                    ),
                    self._loop,
                )
                fut.add_done_callback(_log_send_error)
        if non_block:
            return self._gather_pool.submit(self._finish_step, step_id)
        return self._finish_step(step_id)

    def _on_step_result(
        self, host_rank: int, step_id: int, result, error, spans
    ) -> None:
        """One host's ack for one step (executor loop for remote hosts,
        runner resolve thread for host 0)."""
        if spans:
            get_tracer().adopt(spans)
        with self._inflight_lock:
            entry = self._inflight_steps.get(step_id)
        if entry is None:
            logger.debug(
                "ack for unknown step %d from host %d", step_id, host_rank
            )
            return
        if entry.trace_ctx is not None:
            get_tracer().record_span(
                "executor.gather",
                entry.t_wall,
                max(time.monotonic() - entry.t_mono, 0.0),
                parent=entry.trace_ctx,
                target_host=f"host{host_rank}",
                step_id=step_id,
            )
        if error is not None:
            failure = HostFailure(
                host_rank=host_rank,
                address=entry.origins.get(host_rank, ""),
                phase=PHASE_EXECUTE,
                message=f"step {step_id} failed on host: {error}",
            )
            logger.error("%s — executor failed", failure.describe())
            self._notify_failure(failure)
            return
        with self._inflight_lock:
            entry.done.add(host_rank)
            if host_rank == 0:
                entry.result = result
            if entry.expected <= entry.done:
                # Do NOT pop here: _finish_step owns removal — a fast
                # step completing before the gather-pool thread even
                # looks up the entry must still find it.
                entry.event.set()

    def _finish_step(self, step_id: int):
        """Wait out one step's acks under the execute deadline.  Runs on
        a gather-pool thread (non_block) or the engine thread
        (blocking); either way the wait is bounded and a blown deadline
        names the laggard host(s).  Sole owner of entry removal."""
        with self._inflight_lock:
            entry = self._inflight_steps.get(step_id)
        if entry is None:
            raise RuntimeError(
                "Executor failed."
                if self.is_failed
                else f"step {step_id} has no in-flight record"
            )
        remaining = entry.t_mono + self.execute_timeout - time.monotonic()
        if not entry.event.wait(timeout=max(remaining, 0.0)):
            with self._inflight_lock:
                # Re-check under the lock: the final ack may have landed
                # between the wait timing out and here — that's a
                # completed step, not a deadline miss.
                complete = (
                    entry.error is None and entry.expected <= entry.done
                )
                laggards = sorted(entry.expected - entry.done)
            if not complete:
                names = ", ".join(
                    f"rank {r} ({entry.origins.get(r, '?')})"
                    for r in laggards
                ) or "unknown"
                first = laggards[0] if laggards else 0
                failure = HostFailure(
                    host_rank=first,
                    address=entry.origins.get(first, ""),
                    phase=PHASE_EXECUTE,
                    message=(
                        f"step dispatch deadline "
                        f"({self.execute_timeout:.0f}s) missed by: {names}"
                    ),
                )
                logger.error("%s", failure.describe())
                self._notify_failure(failure)
                entry.error = entry.error or failure
                entry.event.set()
        with self._inflight_lock:
            self._inflight_steps.pop(step_id, None)
        if entry.error is not None:
            detail = (
                entry.error.describe()
                if isinstance(entry.error, HostFailure)
                else str(entry.error)
            )
            raise RuntimeError(f"Executor failed: {detail}")
        return entry.result

    def _fail_inflight_steps(self, error: HostFailure | str) -> None:
        """Release every engine-side waiter with the failure — a dead
        deployment must never leave a `_finish_step` blocked until its
        deadline when the cause is already known.  Entries stay in the
        map (each `_finish_step` pops its own); on a dead deployment
        the executor object is discarded wholesale, so unclaimed
        entries cannot outlive it."""
        with self._inflight_lock:
            entries = list(self._inflight_steps.values())
        for entry in entries:
            if entry.error is None:
                entry.error = error
            entry.event.set()

    def step_stream_stats(self) -> dict:
        """Per-host run-loop stats ({dispatched, resolved, stalls,
        inflight, max_queue_depth}) for the bench harness and the
        dispatch microbench."""
        stats: dict[str, dict] = {}
        if self._local_runner is not None:
            stats["host0"] = self._local_runner.stats()
        for host in self._remote_hosts:
            if host.worker is None:
                continue
            try:
                stats[f"host{host.host_rank}"] = (
                    asyncio.run_coroutine_threadsafe(
                        host.worker.get_step_stream_stats(), self._loop
                    ).result(timeout=10)
                )
            except Exception as e:  # noqa: BLE001 — stats are
                # best-effort introspection.
                logger.debug(
                    "host %d stream stats failed: %s", host.host_rank, e
                )
        return stats

    def _step_trace_ctx(self, method: str, args: tuple):
        """Trace context for a step-shaped collective: the scheduler
        stamps SchedulerOutput.trace_ctx with the first traced request's
        root context.  None (the common case: tracing off, untraced
        request, init collectives) keeps every span below a no-op."""
        if method not in ("execute_model", "dispatch_model") or not args:
            return None
        if not get_tracer().enabled:
            return None
        return getattr(args[0], "trace_ctx", None)

    @staticmethod
    def _payload_bytes(payload) -> int:
        """Serialized control-message size attached to dispatch spans
        (only computed while tracing; the transport pickles again)."""
        import cloudpickle

        try:
            return len(cloudpickle.dumps(payload))
        except Exception:  # noqa: BLE001 — attribute is best-effort
            return -1

    @staticmethod
    def _dispatch_span(ctx, host_rank, method, payload_bytes=None,
                       step_id=None):
        if ctx is None:
            return NOOP_SPAN
        attrs = {"target_host": f"host{host_rank}", "method": method}
        if payload_bytes is not None:
            attrs["payload_bytes"] = payload_bytes
        if step_id is not None:
            attrs["step_id"] = step_id
        return get_tracer().span("executor.dispatch", parent=ctx, **attrs)

    def _gather(self, futures, origins, unique_reply_rank, timeout, phase,
                trace_ctx=None, step_id=None):
        # One overall deadline, not timeout × num_hosts; a blown deadline
        # or a failed reply is attributed to the offending host(s).
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        tracer = get_tracer()
        results = []
        for fut, (host_rank, address) in zip(futures, origins):
            attrs = {"target_host": f"host{host_rank}"}
            if step_id is not None:
                attrs["step_id"] = step_id
            span = (
                tracer.span("executor.gather", parent=trace_ctx, **attrs)
                if trace_ctx is not None
                else NOOP_SPAN
            )
            try:
                with span:
                    results.append(
                        fut.result(
                            timeout=None
                            if deadline is None
                            else max(deadline - time.monotonic(), 0.0)
                        )
                    )
            except concurrent.futures.TimeoutError as e:
                laggards = [
                    o for f, o in zip(futures, origins) if not f.done()
                ]
                names = ", ".join(
                    f"rank {r} ({a})" for r, a in laggards
                ) or f"rank {host_rank} ({address})"
                first = laggards[0] if laggards else (host_rank, address)
                failure = HostFailure(
                    host_rank=first[0],
                    address=first[1],
                    phase=phase,
                    message=(
                        f"{method_desc(phase)} deadline ({timeout:.0f}s) "
                        f"missed by: {names}"
                    ),
                )
                logger.error("%s", failure.describe())
                self._notify_failure(failure)
                raise RuntimeError(
                    f"Executor failed: {failure.describe()}"
                ) from e
            except Exception as e:  # noqa: BLE001
                failure = HostFailure.from_exception(
                    host_rank, address, phase, "collective reply failed", e
                )
                logger.error("collective_rpc failed: %s", failure.describe())
                self._notify_failure(failure)
                raise RuntimeError(
                    f"Executor failed: {failure.describe()}"
                ) from e
        if unique_reply_rank is not None:
            return results[unique_reply_rank]
        return results

    @property
    def output_rank(self) -> int:
        return 0  # SPMD: host 0's copy of the output is canonical.

    @property
    def num_reply_workers(self) -> int:
        return self.num_hosts

    def _notify_failure(self, failure: HostFailure | None = None) -> None:
        # Errors during an intentional shutdown are teardown noise, not
        # deployment failures — don't mark the engine dead for them.
        if getattr(self, "_shutting_down", False):
            return
        super()._notify_failure(failure)
        # Any failure path (heartbeat, EOF, step error, deadline) must
        # release step-stream waiters immediately with the root cause —
        # detection stays EOF-fast instead of deadline-slow.
        cause = self.failure_info
        self._fail_inflight_steps(
            cause if cause is not None else "executor failed"
        )

    def shutdown(self) -> None:
        self._shutting_down = True
        self._teardown(drain_workers=True)

    def _teardown(self, drain_workers: bool) -> None:
        """Restartable teardown: by the time this returns, the listening
        socket is released (awaited on the executor loop, not merely
        scheduled) and the loop thread has been joined — so a supervisor
        rebuilding the executor (engine/supervisor.py) can immediately
        re-listen on the same port.  Safe to call more than once."""
        self._cancel_heartbeats()
        # Close the listener FIRST: the port must be re-bindable the
        # instant teardown begins.  Test/compose respawners fork new
        # agent processes within ~100ms of a kill, and a fork taken
        # while this socket is still open would inherit the bound fd
        # and hold the port against the supervisor's rebuilt executor.
        server = getattr(self, "_server", None)
        if server is not None:
            self._server = None
            try:
                asyncio.run_coroutine_threadsafe(
                    self._close_server(server), self._loop
                ).result(timeout=5)
            except Exception as e:  # noqa: BLE001
                logger.debug("listener close failed: %s", e)
        # Release engine-side step waiters and stop the local run loop:
        # gather-pool threads blocked in _finish_step must wake now, not
        # at their deadline.
        self._fail_inflight_steps("executor shutdown")
        runner, self._local_runner = getattr(
            self, "_local_runner", None
        ), None
        if runner is not None:
            runner.stop()
        if drain_workers and not self.is_failed:
            if getattr(self, "_streams_started", False):
                # Stop remote run loops first so their worker threads
                # are joined before the jax.distributed shutdown
                # barrier below.
                for host in self._remote_hosts:
                    if host.worker is None:
                        continue
                    try:
                        asyncio.run_coroutine_threadsafe(
                            host.worker.stop_step_stream(), self._loop
                        ).result(timeout=5)
                    except Exception as e:  # noqa: BLE001 — teardown
                        # is best-effort on each host.
                        logger.debug(
                            "stop_step_stream on host %d failed: %s",
                            host.host_rank,
                            e,
                        )
            # Clean jax.distributed teardown on every host BEFORE dropping
            # the control plane (the shutdown barrier needs all tasks).
            # Pointless on a failed deployment: the collective would just
            # raise "Executor failed" immediately.
            try:
                self.collective_rpc("shutdown", timeout=15.0)
            except Exception as e:  # noqa: BLE001 — failed/partial
                # deployments tear down as far as they can.
                logger.debug("shutdown collective failed: %s", e)
        for host in self._remote_hosts:
            try:
                host.peer.kill("executor shutdown")
                if host.transport is not None:
                    # Stream writers belong to the executor loop; close
                    # them there, before the stop() queued below.
                    self._loop.call_soon_threadsafe(host.transport.close)
            except Exception as e:  # noqa: BLE001
                logger.debug("peer teardown failed: %s", e)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._loop_thread.join(timeout=5)
        self._local_pool.shutdown(wait=False)
        self._local_fetch_pool.shutdown(wait=False)
        self._gather_pool.shutdown(wait=False)

    @staticmethod
    async def _close_server(server) -> None:
        server.close()
        await server.wait_closed()


def _log_send_error(fut) -> None:
    e = fut.exception()
    if e is not None:
        logger.debug("step frame send failed: %s", e)


def method_desc(phase: str) -> str:
    return {
        PHASE_INIT: "worker init collective",
        PHASE_EXECUTE: "collective reply",
    }.get(phase, "collective reply")
